"""Per-tenant usage metering (ISSUE 17): device-time attribution,
KV page-second ledger, terminal-state audit, and tenant-scoped
SLO/fleet views.

Tier-1 acceptance pins:

- EXACT conservation under chaos: with a seeded fault schedule firing
  >=3 distinct sites, every work phase's ledger-attributed float ms is
  BITWISE equal to the ``serve.step.<phase>_ms`` histogram total, the
  integer-ns per-request split partitions each observation exactly,
  and ``unattributed_ms`` is exactly 0.0
  (``TestConservationChaos``);
- killing 1 of 2 replicas mid-load keeps the FLEET ledger conserved
  and exactly-once: every request appears once in the folded
  ``fleet_usage`` view in the ``ok`` state with its device-ns summed
  across the replicas that actually served it
  (``TestFleetConservation``);
- every submitted request ends with EXACTLY ONE closed usage record
  in a terminal state from {ok, error, deadline_exceeded, shed,
  unserved} (``TestTerminalAudit``);
- ``FLAGS_usage_ledger`` off (the default) means NO ledger object and
  ZERO accounting calls on the serve path — pinned by poisoning every
  UsageLedger method (``TestLedgerOff``);
- ``serve_bench --tenants 8 --usage-out`` runs end-to-end on CPU and
  its JSONL reconciles with the bench's own token throughput
  (``TestBenchCLI``), and ``trace_merge`` + ``serve_top --tenants``
  round-trip a fleet export (``TestMergeTopCLI``).
"""
import json
import os
import subprocess
import sys
import tempfile
import types

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import stats
from paddle_tpu.profiler import alerts as alerts_mod
from paddle_tpu.inference import FusedCausalLM
from paddle_tpu.serving import (FaultInjector, FleetRouter,
                                ManualClock, PoolSizingError,
                                ServerOverloaded, ServingEngine,
                                SLOConfig, use_clock)
from paddle_tpu.serving import accounting
from paddle_tpu.serving.accounting import (DEFAULT_TENANT,
                                           TERMINAL_STATES,
                                           UsageLedger, WORK_PHASES,
                                           fold_records,
                                           load_usage_jsonl,
                                           tenant_rollup,
                                           unattributed_ms)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model(seed=7, max_position=256):
    paddle.seed(seed)
    return FusedCausalLM(vocab_size=64, embed_dim=32, num_heads=4,
                         dim_feedforward=64, num_layers=2,
                         max_position=max_position)


def _engine(model, faults=None, **kw):
    kw.setdefault("max_batch", 3)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_length", 128)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("slo", SLOConfig(prefill_chunk=16))
    return ServingEngine(model, faults=faults, **kw)


def _router(n=2, seed=7, policy="affinity", faults=None, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_length", 96)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("slo", SLOConfig(prefill_chunk=8))
    return FleetRouter(
        engine_factory=lambda i: ServingEngine(_model(seed), **kw),
        n_replicas=n, policy=policy, faults=faults)


def _prompts(lens=(6, 10, 14, 9), seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 64, (L,)) for L in lens]


class _flags:
    """Scoped flag override (flags are process-global)."""

    def __init__(self, **kw):
        self._new = {f"FLAGS_{k}": v for k, v in kw.items()}

    def __enter__(self):
        self._old = paddle.get_flags(list(self._new))
        paddle.set_flags(self._new)
        return self

    def __exit__(self, *exc):
        paddle.set_flags(self._old)


@pytest.fixture(autouse=True)
def _restore_usage_flags():
    names = ["FLAGS_usage_ledger", "FLAGS_usage_tenants_max",
             "FLAGS_usage_top_k"]
    old = paddle.get_flags(names)
    yield
    paddle.set_flags(old)


def _tools(name):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _fake_req(rid=1, tenant="t0"):
    return types.SimpleNamespace(id=rid, tenant=tenant)


def _assert_conserved(eng):
    """The tentpole invariant on one engine: per-phase BITWISE float
    equality with the stats histograms, exact integer-ns partition,
    and zero unattributed device time."""
    u = eng.usage
    led_ms = u.attributed_ms()
    led_n = u.phase_counts()
    _, _, hists = stats.sample_values()
    seen = 0
    for ph in WORK_PHASES:
        h = hists.get(f"serve.step.{ph}_ms")
        if h is None:
            assert ph not in led_ms or led_ms[ph] == 0.0
            continue
        seen += 1
        count, total = h
        assert led_ms.get(ph, 0.0) == total, ph   # bitwise ==
        assert led_n.get(ph, 0) == count, ph
    assert seen, "no work phase observed at all"
    # integer-ns conservation: per-request shares + the system residue
    # re-add to the per-phase ns totals EXACTLY
    per_req: dict = {}
    for rec in u.records():
        for ph, ns in rec["phase_ns"].items():
            per_req[ph] = per_req.get(ph, 0) + ns
    sys_ns = u.system_ns_totals()
    for ph, ns in u.phase_ns_totals().items():
        assert per_req.get(ph, 0) + sys_ns.get(ph, 0) == ns, ph
    assert unattributed_ms(u) == 0.0


# =====================================================================
# tentpole: exact conservation under chaos
# =====================================================================

@pytest.mark.chaos
class TestConservationChaos:
    def test_single_engine_chaos_exact_conservation(self):
        """Seeded faults at >=3 distinct sites (pool squeeze, prefill
        dispatch raise, decode raise, prefix-insert raise): the
        retry/requeue churn makes attribution genuinely hard, and the
        ledger still conserves bitwise."""
        stats.reset()
        inj = (FaultInjector()
               .add("kv.grow", kind="raise", at=1)
               .add("prefill.dispatch", kind="raise", at=2)
               .add("decode.step", kind="raise", at=2)
               .add("decode.step", kind="squeeze", pages=6, at=5)
               .add("prefix.insert", kind="raise", at=0))
        with _flags(usage_ledger=True):
            eng = _engine(_model(), faults=inj)
            rids = [eng.submit(p, max_new_tokens=6,
                               tenant=f"t{i % 2}")
                    for i, p in enumerate(_prompts((37, 6, 9, 12)))]
            done = {r.id: r for r in eng.run()}
        assert len({f["site"] for f in inj.fired}) >= 3
        for rid in rids:
            assert done[rid].state in TERMINAL_STATES
        _assert_conserved(eng)
        # every submitted request has exactly one CLOSED record
        closed = {r["rid"]: r for r in
                  eng.usage.records(include_open=False)}
        assert set(closed) == set(rids)
        for rid in rids:
            assert closed[rid]["state"] == done[rid].state

    def test_clean_run_conserves_and_rolls_up_tenants(self):
        stats.reset()
        with _flags(usage_ledger=True):
            eng = _engine(_model())
            rids = [eng.submit(p, max_new_tokens=5,
                               tenant=("alpha", "beta")[i % 2])
                    for i, p in enumerate(_prompts())]
            done = {r.id: r for r in eng.run()}
        assert all(done[r].state == "ok" for r in rids)
        _assert_conserved(eng)
        roll = tenant_rollup(eng.usage.records())
        assert set(roll) == {"alpha", "beta"}
        assert sum(a["n_requests"] for a in roll.values()) == len(rids)
        # shares partition the attributed device time
        assert sum(a["share"] for a in roll.values()) \
            == pytest.approx(1.0, abs=1e-6)
        # decode tokens reconcile with what each request generated
        n_decode = sum(a["decode_tokens"] for a in roll.values())
        assert n_decode == sum(len(done[r].generated) for r in rids)

    def test_untenanted_requests_bill_default_tenant(self):
        with _flags(usage_ledger=True):
            eng = _engine(_model())
            rid = eng.submit(np.arange(6), max_new_tokens=3)
            eng.run()
        rec = eng.usage.record_of(rid)
        assert rec["tenant"] == DEFAULT_TENANT


# =====================================================================
# KV page-seconds on the manual clock (hand-computed trajectory)
# =====================================================================

class TestPageSeconds:
    def test_hand_computed_page_second_integral(self):
        clk = ManualClock(100.0)
        u = UsageLedger(clock=clk.now)
        r = _fake_req()
        u.set_pages(r, 2)           # t=100.0: 0 pages before -> free
        clk.advance(0.5)
        u.set_pages(r, 5)           # +2 * 0.5
        clk.advance(0.25)
        u.set_pages(r, 0)           # +5 * 0.25
        clk.advance(1.0)            # holding 0 pages: no charge
        snap = u.finish(r, "ok")
        assert snap["kv_page_s"] == pytest.approx(2 * 0.5 + 5 * 0.25)

    def test_finish_closes_open_page_integral(self):
        clk = ManualClock(0.0)
        u = UsageLedger(clock=clk.now)
        r = _fake_req()
        u.set_pages(r, 3)
        clk.advance(2.0)
        snap = u.finish(r, "ok")    # close integrates the open span
        assert snap["kv_page_s"] == pytest.approx(6.0)

    def test_queue_seconds_and_events(self):
        u = UsageLedger()
        r = _fake_req()
        u.note_queue(r, 0.125)
        u.add_event(r, retry=2, preempt=1, requeue=3)
        u.credit_prefix(r, 4)
        rec = u.finish(r, "ok")
        assert rec["queue_s"] == pytest.approx(0.125)
        assert rec["retries"] == 2
        assert rec["preemptions"] == 1
        assert rec["requeues"] == 3
        assert rec["prefix_pages_saved"] == 4

    def test_charge_phase_partitions_ns_exactly(self):
        u = UsageLedger()
        reqs = [_fake_req(i, f"t{i}") for i in range(3)]
        u.charge_phase("decode_chunk", 0.0100001, reqs)
        total = round(0.0100001 * 1e6)
        shares = [u.record_of(r.id)["phase_ns"]["decode_chunk"]
                  for r in reqs]
        assert sum(shares) == total           # exact partition
        assert max(shares) - min(shares) <= 1  # fair to the ns
        assert u.phase_counts()["decode_chunk"] == 1
        assert u.attributed_ms()["decode_chunk"] == 0.0100001

    def test_empty_target_list_lands_on_system(self):
        u = UsageLedger()
        u.charge_phase("decode_chunk", 1.5, ())
        assert u.system_ns_totals()["decode_chunk"] \
            == round(1.5 * 1e6)
        assert u.attributed_ms()["decode_chunk"] == 1.5
        assert not u.records()


# =====================================================================
# fleet: replica-kill failover + migration stay exactly-once
# =====================================================================

@pytest.mark.chaos
class TestFleetConservation:
    def test_kill_one_of_two_fleet_ledger_exactly_once(self):
        """The PR's fleet pin: a replica dies mid-load, every request
        finishes on the survivor, and the FOLDED fleet ledger charges
        each exactly once — device-ns summed over both hops, one
        terminal ``ok`` state, zero unattributed time."""
        stats.reset()
        with _flags(usage_ledger=True):
            router = _router(2)
            prompts = _prompts()
            rids = [router.submit(p, max_new_tokens=6,
                                  tenant=f"t{i % 2}")
                    for i, p in enumerate(prompts)]
            for _ in range(3):
                router.step()
            victim = next(r.idx for r in router.replicas
                          if r.eng.has_work)
            router.kill(victim)
            done = {r.id: r for r in router.run()}
        assert all(done[r].state == "ok" for r in rids)
        folded = router.fleet_usage()
        by_rid = {}
        for rec in folded:
            assert rec["rid"] not in by_rid, "rid charged twice"
            by_rid[rec["rid"]] = rec
        assert set(by_rid) == set(rids)
        for rid in rids:
            assert by_rid[rid]["state"] == "ok"
        # a failed-over request's record folds across >1 hop
        assert any(r["hops"] > 1 for r in folded) or \
            stats.counter("fleet.failover_requests").value >= 1
        # fleet conservation: Sum ledger ns == Sum histogram ms within
        # one rounding quantum per observation; no unattributed time
        ledgers = [rep.eng.usage for rep in router.replicas
                   if rep.eng.usage is not None]
        if router.usage is not None:
            ledgers.append(router.usage)
        assert unattributed_ms(*ledgers) == 0.0
        _, _, hists = stats.sample_values()
        for ph in WORK_PHASES:
            h = hists.get(f"serve.step.{ph}_ms")
            if h is None:
                continue
            count, total = h
            led_ns = sum(u.phase_ns_totals().get(ph, 0)
                         for u in ledgers)
            assert led_ns / 1e6 == pytest.approx(
                total, abs=count * 0.5e-6 + 1e-9), ph

    def test_drain_migration_charged_once_on_destination(self):
        stats.reset()
        with _flags(usage_ledger=True):
            router = _router(2)
            rids = [router.submit(p, max_new_tokens=8, tenant="mig")
                    for p in _prompts((12, 10))]
            for _ in range(6):          # get slots mid-decode
                router.step()
            src = next((r.idx for r in router.replicas
                        if r.eng.num_active), None)
            if src is not None:
                router.drain(src)
            done = {r.id: r for r in router.run()}
        assert all(done[r].state == "ok" for r in rids)
        folded = {r["rid"]: r for r in router.fleet_usage()}
        assert set(folded) == set(rids)
        mig_ns = sum(r["phase_ns"].get("migration", 0)
                     for r in folded.values())
        _, _, hists = stats.sample_values()
        h = hists.get("serve.step.migration_ms")
        if h is not None:               # a migration actually ran
            assert mig_ns / 1e6 == pytest.approx(
                h[1], abs=h[0] * 0.5e-6 + 1e-9)
        ledgers = [rep.eng.usage for rep in router.replicas]
        ledgers.append(router.usage)
        assert unattributed_ms(*[u for u in ledgers
                                 if u is not None]) == 0.0


# =====================================================================
# terminal-state audit: every request closes exactly once
# =====================================================================

@pytest.mark.chaos
class TestTerminalAudit:
    def test_ok_closes_once_and_refuses_double_close(self):
        u = UsageLedger()
        r = _fake_req()
        u.add_tokens(r, decode=3)
        assert u.finish(r, "ok") is not None
        assert u.finish(r, "error") is None      # exactly-once
        assert u.record_of(r.id)["state"] == "ok"

    def test_persistent_fault_closes_error(self):
        inj = FaultInjector().add("prefill.dispatch", kind="raise",
                                  every=1, times=-1)
        with _flags(usage_ledger=True):
            eng = _engine(_model(), faults=inj)
            rids = [eng.submit(p, max_new_tokens=4)
                    for p in _prompts((6, 9))]
            done = {r.id: r for r in eng.run()}
        assert all(done[r].state == "error" for r in rids)
        recs = {r["rid"]: r for r in
                eng.usage.records(include_open=False)}
        assert set(recs) == set(rids)
        assert all(recs[r]["state"] == "error" for r in rids)
        assert all(recs[r]["retries"] > 0 for r in rids)

    def test_deadline_closes_deadline_exceeded(self):
        with _flags(usage_ledger=True), \
                use_clock(ManualClock()) as clk:
            eng = _engine(_model(), max_batch=1)
            r_ok = eng.submit(np.arange(6) + 1, max_new_tokens=4)
            r_dead = eng.submit(np.arange(9) + 2, max_new_tokens=4,
                                deadline_ms=50.0)
            clk.advance(0.2)
            done = {r.id: r for r in eng.run()}
        assert done[r_dead].state == "deadline_exceeded"
        assert done[r_ok].state == "ok"
        recs = {r["rid"]: r for r in
                eng.usage.records(include_open=False)}
        assert recs[r_dead]["state"] == "deadline_exceeded"
        assert recs[r_ok]["state"] == "ok"

    def test_shed_at_submit_closes_shed(self):
        with _flags(usage_ledger=True, serve_inbox_limit=2):
            eng = _engine(_model())
            eng.submit(np.arange(4), max_new_tokens=2)
            eng.submit(np.arange(4), max_new_tokens=2)
            with pytest.raises(ServerOverloaded):
                eng.submit(np.arange(4), max_new_tokens=2,
                           tenant="noisy")
            shed = [r for r in eng.usage.records(include_open=False)
                    if r["state"] == "shed"]
            assert len(shed) == 1
            assert shed[0]["tenant"] == "noisy"
            eng.run()

    def test_crash_exit_closes_unserved(self, tmp_path):
        """A config crash aborts the loop with a request still
        waiting — the audit closes it as ``unserved`` so its queue
        time is not silently lost."""
        with _flags(usage_ledger=True,
                    serve_journal_dir=str(tmp_path)):
            eng = _engine(_model(), max_batch=1, max_length=64,
                          num_pages=15, slo=SLOConfig(prefill_chunk=8))
            rng = np.random.RandomState(37)
            r_big = eng.submit(rng.randint(0, 64, (56,)),
                               max_new_tokens=8)
            r_wait = eng.submit(np.arange(5), max_new_tokens=2,
                                tenant="queued")
            with pytest.raises(PoolSizingError):
                eng.run()
        states = {r["rid"]: r["state"]
                  for r in eng.usage.records(include_open=False)}
        assert states.get(r_wait) == "unserved"
        assert r_big not in states or \
            states[r_big] in TERMINAL_STATES

    def test_fold_state_precedence_and_hop_dedup(self):
        """A dispatch-retried request can close ``shed`` on replica A
        and ``ok`` on replica B — the fold resolves by rank (ok wins),
        and re-merging the same hop's dump adds nothing."""
        base = {"type": "usage", "tenant": "t", "queue_s": 0.0,
                "kv_page_s": 0.0, "prefill_tokens": 0,
                "decode_tokens": 0, "spec_accepted_tokens": 0,
                "wasted_tokens": 0, "retries": 0, "preemptions": 0,
                "requeues": 0, "prefix_pages_saved": 0}
        a = dict(base, rid=1, state="shed", hop=0,
                 phase_ns={"decode_chunk": 100})
        b = dict(base, rid=1, state="ok", hop=1,
                 phase_ns={"decode_chunk": 250})
        folded = fold_records([a, b, dict(a)])   # hop 0 twice
        assert len(folded) == 1
        rec = folded[0]
        assert rec["state"] == "ok"              # rank precedence
        assert rec["phase_ns"]["decode_chunk"] == 350  # deduped
        assert rec["hops"] == 2


# =====================================================================
# flag off: zero ledger, zero accounting calls
# =====================================================================

class TestLedgerOff:
    def test_flag_off_means_no_ledger_and_zero_calls(self,
                                                     monkeypatch):
        """The PR 9 journal-off pin, replayed for the ledger: with
        ``FLAGS_usage_ledger`` off (the default) the serve path must
        never touch ANY UsageLedger method — each one is poisoned."""
        paddle.set_flags({"FLAGS_usage_ledger": False})

        def boom(*a, **kw):
            raise AssertionError("UsageLedger touched with flag off")

        for name in ("charge_phase", "set_pages", "note_queue",
                     "add_tokens", "add_event", "credit_prefix",
                     "finish", "publish_gauges"):
            monkeypatch.setattr(UsageLedger, name, boom)
        eng = _engine(_model())
        assert eng.usage is None
        assert eng._usage is None
        rid = eng.submit(np.arange(8), max_new_tokens=4,
                         tenant="ignored")
        done = {r.id: r for r in eng.run()}
        assert done[rid].state == "ok"

    def test_flag_off_router_has_no_ledger(self):
        paddle.set_flags({"FLAGS_usage_ledger": False})
        router = _router(2)
        assert router.usage is None
        assert all(rep.eng.usage is None for rep in router.replicas)
        assert router.fleet_usage() == []
        with tempfile.TemporaryDirectory() as d:
            assert router.export_usage(d) == []


# =====================================================================
# tenant-scoped SLO windows, gauges, alerting
# =====================================================================

class TestTenantViews:
    def _run_tenants(self, tenants, max_new=3):
        eng = _engine(_model())
        prompts = _prompts(tuple(6 + 2 * i for i in range(len(tenants))))
        rids = [eng.submit(p, max_new_tokens=max_new, tenant=t)
                for p, t in zip(prompts, tenants)]
        done = {r.id: r for r in eng.run()}
        return eng, rids, done

    def test_per_tenant_goodput_windows(self):
        with _flags(usage_ledger=True):
            eng, rids, done = self._run_tenants(["a", "a", "b"])
        g = eng.slo_monitor.tenant_goodputs()
        assert set(g) == {"a", "b"}
        assert all(0.0 <= v <= 1.0 for v in g.values())
        mg = eng.slo_monitor.tenant_min_goodput
        assert mg == pytest.approx(min(g.values()))

    def test_tenant_window_overflow_buckets_other(self):
        with _flags(usage_ledger=True, usage_tenants_max=2):
            eng, rids, done = self._run_tenants(
                ["t0", "t1", "t2", "t3"])
        g = eng.slo_monitor.tenant_goodputs()
        assert "__other__" in g
        assert len(g) <= 3          # 2 named + overflow bucket

    def test_publish_gauges_bounded_cardinality(self):
        stats.reset()
        with _flags(usage_ledger=True, usage_top_k=2):
            eng, rids, done = self._run_tenants(["a", "b", "c"])
            eng.usage.publish_gauges(top_k=2)
        assert stats.gauge("tenant.count").value == 3
        assert 0.0 < stats.gauge("tenant.max_share").value <= 1.0
        assert stats.gauge("usage.records").value == len(rids)
        # index-keyed topN: bounded names, no per-tenant explosion
        names = {n for n in stats.snapshot()["gauges"]
                 if n.startswith("tenant.top")}
        assert names <= {"tenant.top0.device_ms",
                         "tenant.top1.device_ms"}

    def test_tenant_hog_rule_in_default_alerts(self):
        rules = alerts_mod.default_rules()
        hog = [r for r in rules if r.name == "tenant-hog"]
        assert len(hog) == 1
        assert hog[0].metric == "tenant.max_share"
        assert hog[0].threshold == pytest.approx(0.8)

    def test_wasted_chunk_tail_charged_to_finisher(self):
        """decode_chunk=2 with max_new=4 finishes mid-chunk: the
        executed-but-discarded tail tokens land on the finisher's
        record and reconcile with the global waste counter."""
        stats.reset()
        with _flags(usage_ledger=True):
            eng = _engine(_model(), slo=SLOConfig(
                prefill_chunk=16, prefix_cache=False))
            rids = [eng.submit(p, max_new_tokens=4)
                    for p in _prompts((7, 11))]
            eng.run()
        wasted = sum(r["wasted_tokens"] for r in eng.usage.records())
        assert wasted == int(
            stats.counter("serving.wasted_decode_tokens").value)
        roll = tenant_rollup(eng.usage.records())
        for agg in roll.values():
            assert 0.0 <= agg["waste_share"] <= 1.0

    def test_prefix_share_credited(self):
        """The second request over an identical prompt reuses cached
        prefix pages; the ledger credits the pages it did NOT have to
        prefill."""
        with _flags(usage_ledger=True):
            eng = _engine(_model())
            p = np.arange(12) % 64
            r1 = eng.submit(p, max_new_tokens=2)
            eng.run()
            r2 = eng.submit(p, max_new_tokens=2)
            eng.run()
        rec2 = eng.usage.record_of(r2)
        if stats.counter("serving.prefix_hit").value:
            assert rec2["prefix_pages_saved"] > 0


# =====================================================================
# tools: gate directions, tenant table, fold round-trip
# =====================================================================

class TestTools:
    def test_bench_gate_gates_usage_rungs(self):
        bench_gate = _tools("bench_gate")
        m = bench_gate.DEFAULT_METRICS
        assert m["serve_tenant_max_share"] == "up"
        assert m["usage_unattributed_ms"] == "up"

    def test_serve_top_render_tenants_table(self):
        serve_top = _tools("serve_top")
        base = {"tenant": "acme", "rid": 1, "state": "ok",
                "phase_ns": {"decode_chunk": 2_000_000},
                "device_ms": 2.0, "queue_s": 0.01, "kv_page_s": 0.5,
                "prefill_tokens": 8, "decode_tokens": 4,
                "spec_accepted_tokens": 0, "wasted_tokens": 1,
                "retries": 0, "preemptions": 0, "requeues": 0,
                "prefix_pages_saved": 0}
        other = dict(base, tenant="beta", rid=2,
                     phase_ns={"decode_chunk": 6_000_000},
                     device_ms=6.0, wasted_tokens=0)
        txt = serve_top.render_tenants([base, other], accounting)
        assert "acme" in txt and "beta" in txt
        assert "waste" in txt
        # sorted by device time: beta (6ms) above acme (2ms)
        assert txt.index("beta") < txt.index("acme")

    def test_serve_top_engine_view_reports_disabled(self):
        serve_top = _tools("serve_top")
        paddle.set_flags({"FLAGS_usage_ledger": False})
        eng = _engine(_model())
        txt = serve_top.render_tenants_engine(eng)
        assert "usage" in txt.lower()

    def test_dump_load_fold_round_trip(self, tmp_path):
        with _flags(usage_ledger=True):
            eng = _engine(_model())
            rids = [eng.submit(p, max_new_tokens=3, tenant="rt")
                    for p in _prompts((6, 9))]
            eng.run()
        path = str(tmp_path / "usage_r0.jsonl")
        eng.usage.dump_jsonl(path, hop=0)
        loaded = load_usage_jsonl(path)
        assert {r["rid"] for r in loaded} == set(rids)
        folded = fold_records(loaded + loaded)   # same hop: dedup
        assert len(folded) == len(rids)
        want = {r["rid"]: r["phase_ns"] for r in loaded}
        for rec in folded:
            assert rec["phase_ns"] == want[rec["rid"]]


# =====================================================================
# CLI end-to-end (subprocess, CPU)
# =====================================================================

@pytest.mark.chaos
class TestBenchCLI:
    def test_serve_bench_tenants_reconciles(self, tmp_path):
        """CLI pin: ``--tenants 8 --usage-out`` emits the tenant
        rungs, writes a JSONL whose closed records cover every served
        request, reports zero unattributed time, and the ledger's
        decode tokens reconcile with the bench's own throughput."""
        usage_path = str(tmp_path / "usage.jsonl")
        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "serve_bench.py"),
             "--streams", "2", "--requests", "8", "--max-new", "4",
             "--prompt-mix", "8,24", "--prefill-chunk", "16",
             "--decode-chunk", "4", "--rate", "500", "--no-lint",
             "--tenants", "8", "--tenant-skew", "1.0",
             "--usage-out", usage_path],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["serve_tenant_count"] >= 2
        assert 0.0 < out["serve_tenant_max_share"] <= 1.0
        assert out["usage_unattributed_ms"] == 0.0
        if out["serve_tenant_min_goodput"] is not None:
            assert 0.0 <= out["serve_tenant_min_goodput"] <= 1.0
        recs = load_usage_jsonl(usage_path)
        closed = [r for r in recs if r["state"] is not None]
        assert len(closed) == out["serve_requests"]
        assert all(r["state"] in TERMINAL_STATES for r in closed)
        assert len({r["tenant"] for r in closed}) \
            == out["serve_tenant_count"]
        # throughput reconciliation: the ledger's decode tokens are
        # the same tokens serve_tokens_per_sec counted
        n_decode = sum(r["decode_tokens"] for r in recs)
        bench_tokens = out["serve_tokens_per_sec"] * out["serve_wall_s"]
        assert n_decode == pytest.approx(
            bench_tokens, rel=0.05, abs=2.0)

    def test_serve_bench_without_tenants_emits_off_defaults(self):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "serve_bench.py"),
             "--streams", "1", "--requests", "3", "--max-new", "3",
             "--prompt-mix", "8", "--prefill-chunk", "16",
             "--decode-chunk", "4", "--rate", "500", "--no-lint"],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        # gated keys are ALWAYS present (bench_gate needs both sides)
        assert out["serve_tenant_count"] == 0
        assert out["serve_tenant_max_share"] == 0.0
        assert out["usage_unattributed_ms"] == 0.0


@pytest.mark.chaos
class TestMergeTopCLI:
    def test_fleet_export_merge_top_round_trip(self, tmp_path):
        with _flags(usage_ledger=True):
            router = _router(2)
            rids = [router.submit(p, max_new_tokens=4,
                                  tenant=f"t{i % 3}")
                    for i, p in enumerate(_prompts())]
            done = {r.id: r for r in router.run()}
            assert all(done[r].state == "ok" for r in rids)
            paths = router.export_usage(str(tmp_path))
        assert len(paths) == 3      # 2 replicas + router
        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "trace_merge.py"),
             str(tmp_path)],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["usage_records"] == len(rids)
        merged = out["out_usage"]
        assert merged and os.path.exists(merged)
        folded = [json.loads(line) for line in open(merged)]
        assert {r["rid"] for r in folded} == set(rids)
        # re-merging must not double-count: the merged output is
        # excluded from discovery
        proc2 = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "trace_merge.py"),
             str(tmp_path)],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc2.returncode == 0, proc2.stderr[-2000:]
        out2 = json.loads(proc2.stdout.strip().splitlines()[-1])
        assert out2["usage_records"] == len(rids)
        # serve_top renders the merged fleet ledger offline
        proc3 = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "serve_top.py"),
             "--tenants", merged],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc3.returncode == 0, proc3.stderr[-2000:]
        assert "t0" in proc3.stdout
        assert "device_ms" in proc3.stdout or "device" in proc3.stdout
