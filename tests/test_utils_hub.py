"""paddle.utils / paddle.hub / is_compiled_with_* parity.

Reference targets: python/paddle/utils/{unique_name,deprecated,
dlpack}.py, install_check.py, python/paddle/hapi/hub.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestUtils:
    def test_unique_name_generate_and_guard(self):
        un = paddle.utils.unique_name
        a, b = un.generate("fc"), un.generate("fc")
        assert a != b and a.startswith("fc_")
        with un.guard():
            c = un.generate("fc")
            assert c == "fc_0"  # fresh counter inside the guard
        d = un.generate("fc")
        assert d not in (a, b, c)

    def test_deprecated_warns_and_calls(self):
        @paddle.utils.deprecated(update_to="new_api", since="2.0")
        def old(x):
            return x + 1

        with pytest.warns(DeprecationWarning, match="new_api"):
            assert old(1) == 2

    def test_require_version(self):
        assert paddle.utils.require_version("0.0.0")
        with pytest.raises(RuntimeError):
            paddle.utils.require_version("999.0.0")

    def test_try_import(self):
        assert paddle.utils.try_import("json") is not None
        with pytest.raises(ImportError):
            paddle.utils.try_import("definitely_not_a_module_xyz")

    def test_run_check(self, capsys):
        paddle.utils.run_check()
        assert "successfully" in capsys.readouterr().out

    def test_dlpack_roundtrip(self):
        t = paddle.to_tensor(np.arange(6, dtype=np.float32))
        cap = paddle.utils.dlpack.to_dlpack(t)
        r = paddle.utils.dlpack.from_dlpack(cap)
        np.testing.assert_array_equal(r.numpy(), t.numpy())

    def test_is_compiled_flags(self):
        assert paddle.is_compiled_with_cuda() is False
        assert paddle.is_compiled_with_rocm() is False
        assert paddle.is_compiled_with_custom_device("tpu") is True


class TestHub:
    def _repo(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def toy_model(width=4):\n"
            "    \"\"\"A toy entrypoint.\"\"\"\n"
            "    import paddle_tpu.nn as nn\n"
            "    return nn.Linear(width, width)\n")
        return str(tmp_path)

    def test_list_help_load(self, tmp_path):
        repo = self._repo(tmp_path)
        assert paddle.hub.list(repo) == ["toy_model"]
        assert "toy entrypoint" in paddle.hub.help(repo, "toy_model")
        m = paddle.hub.load(repo, "toy_model", width=3)
        assert tuple(m.weight.shape) == (3, 3)

    def test_remote_source_raises(self, tmp_path):
        with pytest.raises(ValueError, match="local"):
            paddle.hub.list("some/repo", source="github")

    def test_unknown_model_raises(self, tmp_path):
        repo = self._repo(tmp_path)
        with pytest.raises(ValueError):
            paddle.hub.load(repo, "nope")
