"""DatasetFolder / ImageFolder (reference:
python/paddle/vision/datasets/folder.py — directory-tree datasets)."""
import numpy as np
import pytest

from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder


def _make_tree(root, classes=("cat", "dog"), n=3, nested=False):
    for ci, c in enumerate(classes):
        d = root / c / ("sub" if nested else "")
        d.mkdir(parents=True, exist_ok=True)
        for j in range(n):
            arr = np.full((4, 4, 3), 10 * ci + j, np.uint8)
            np.save(str(d / f"img{j}.npy"), arr)


class TestDatasetFolder:
    def test_classes_and_samples(self, tmp_path):
        _make_tree(tmp_path)
        ds = DatasetFolder(str(tmp_path))
        assert ds.classes == ["cat", "dog"]
        assert ds.class_to_idx == {"cat": 0, "dog": 1}
        assert len(ds) == 6
        x, y = ds[0]
        assert x.shape == (4, 4, 3) and y == 0
        assert ds.targets == [0, 0, 0, 1, 1, 1]

    def test_nested_dirs_walked(self, tmp_path):
        _make_tree(tmp_path, nested=True)
        ds = DatasetFolder(str(tmp_path))
        assert len(ds) == 6

    def test_transforms_applied(self, tmp_path):
        _make_tree(tmp_path)
        ds = DatasetFolder(str(tmp_path),
                           transform=lambda a: a.astype(np.float32) / 255,
                           target_transform=lambda t: t + 100)
        x, y = ds[5]
        assert x.dtype == np.float32 and y == 101

    def test_is_valid_file_filter(self, tmp_path):
        _make_tree(tmp_path)
        ds = DatasetFolder(
            str(tmp_path),
            is_valid_file=lambda p: p.endswith("img0.npy"))
        assert len(ds) == 2

    def test_empty_raises(self, tmp_path):
        (tmp_path / "empty_class").mkdir()
        with pytest.raises(RuntimeError, match="Found 0 files"):
            DatasetFolder(str(tmp_path))

    def test_no_classes_raises(self, tmp_path):
        with pytest.raises(RuntimeError, match="no class folders"):
            DatasetFolder(str(tmp_path))

    def test_pil_image_files(self, tmp_path):
        from PIL import Image

        d = tmp_path / "red"
        d.mkdir()
        Image.new("RGB", (8, 8), (255, 0, 0)).save(str(d / "r.png"))
        ds = DatasetFolder(str(tmp_path))
        img, y = ds[0]
        assert np.asarray(img).shape == (8, 8, 3) and y == 0

    def test_dataloader_integration(self, tmp_path):
        import paddle_tpu as paddle

        _make_tree(tmp_path)
        ds = DatasetFolder(str(tmp_path),
                           transform=lambda a: a.astype(np.float32))
        dl = paddle.io.DataLoader(ds, batch_size=3, shuffle=False)
        xb, yb = next(iter(dl))
        assert list(xb.shape) == [3, 4, 4, 3]
        assert list(np.asarray(yb._data).ravel()) == [0, 0, 0]


class TestImageFolder:
    def test_flat_and_unlabeled(self, tmp_path):
        _make_tree(tmp_path)
        np.save(str(tmp_path / "loose.npy"),
                np.zeros((2, 2, 3), np.uint8))
        ds = ImageFolder(str(tmp_path))
        assert len(ds) == 7  # walks root and class dirs
        (sample,) = ds[0]
        assert sample.shape in ((2, 2, 3), (4, 4, 3))

    def test_transform_and_empty(self, tmp_path):
        _make_tree(tmp_path, classes=("a",), n=2)
        ds = ImageFolder(str(tmp_path), transform=lambda a: a.sum())
        (s,) = ds[0]
        assert np.isscalar(s) or getattr(s, "ndim", 1) == 0
        with pytest.raises(RuntimeError, match="Found 0 files"):
            ImageFolder(str(tmp_path / "a" / "nothing_here_mkdir"))
