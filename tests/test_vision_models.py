"""Vision model zoo forward/train smoke (reference:
test/legacy_test vision model tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision.models import (alexnet, mobilenet_v2, resnet18,
                                      vgg11)


@pytest.mark.parametrize("ctor,kwargs,n_params", [
    (alexnet, {}, 57_044_810),
    (vgg11, {}, 128_807_306),
    (vgg11, {"batch_norm": True}, 128_812_810),
    (mobilenet_v2, {}, 2_236_682),
])
def test_forward_shapes_and_param_counts(ctor, kwargs, n_params):
    paddle.seed(0)
    m = ctor(num_classes=10, **kwargs)
    m.eval()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 3, 64, 64).astype(np.float32))
    out = m(x)
    assert out.shape == [2, 10]
    total = sum(int(np.prod(p.shape)) for p in m.parameters())
    assert total == n_params  # matches the reference architectures


def test_mobilenet_trains_a_step():
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    m = mobilenet_v2(scale=0.35, num_classes=4)
    opt = paddle.optimizer.SGD(0.01, parameters=m.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 3, 32, 32).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (4,)))
    out = m(x)
    loss = F.cross_entropy(out, y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    assert np.isfinite(float(loss.numpy()))


def test_pretrained_raises():
    with pytest.raises(NotImplementedError):
        alexnet(pretrained=True)
    # resnet baseline unchanged
    paddle.seed(0)
    r = resnet18(num_classes=10)
    assert len(r.parameters()) > 0


def test_flops_and_summary():
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    f = paddle.flops(net, [1, 1, 28, 28])
    assert f == 347_560  # conv + linear MACs of LeNet at 28x28
    s = paddle.summary(net)
    assert s["total_params"] == 61_610
    assert s["trainable_params"] == 61_610


class TestModelZooExpansion:
    """Round-3 zoo fills (reference: python/paddle/vision/models/
    {squeezenet,densenet,shufflenetv2,googlenet,mobilenetv1,
    inceptionv3}.py): forward shapes + a train step."""

    @pytest.mark.parametrize("ctor,size", [
        (lambda: paddle.vision.models.squeezenet1_1(num_classes=10), 64),
        (lambda: paddle.vision.models.densenet121(num_classes=10), 64),
        (lambda: paddle.vision.models.shufflenet_v2_x0_25(
            num_classes=10), 64),
        (lambda: paddle.vision.models.mobilenet_v1(
            scale=0.25, num_classes=10), 64),
        (lambda: paddle.vision.models.mobilenet_v3_small(
            num_classes=10), 64),
    ])
    def test_forward_shape(self, ctor, size):
        paddle.seed(0)
        m = ctor()
        m.eval()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 3, size, size)
            .astype(np.float32))
        out = m(x)
        assert tuple(out.shape) == (2, 10)

    def test_googlenet_aux_heads(self):
        paddle.seed(0)
        m = paddle.vision.models.googlenet(num_classes=10)
        x = paddle.to_tensor(np.random.RandomState(1)
                             .randn(1, 3, 96, 96).astype(np.float32))
        m.train()
        out, aux1, aux2 = m(x)
        assert tuple(out.shape) == (1, 10)
        assert tuple(aux1.shape) == (1, 10)
        assert tuple(aux2.shape) == (1, 10)
        m.eval()
        out, aux1, aux2 = m(x)
        assert aux1 is None and aux2 is None

    def test_inception_v3_forward(self):
        paddle.seed(0)
        m = paddle.vision.models.inception_v3(num_classes=10)
        m.eval()
        x = paddle.to_tensor(np.random.RandomState(2)
                             .randn(1, 3, 299, 299).astype(np.float32))
        assert tuple(m(x).shape) == (1, 10)

    def test_small_model_trains(self):
        paddle.seed(3)
        m = paddle.vision.models.shufflenet_v2_x0_25(num_classes=4)
        opt = paddle.optimizer.Adam(1e-3, parameters=m.parameters())
        rng = np.random.RandomState(3)
        x = paddle.to_tensor(rng.randn(4, 3, 32, 32).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (4,)))
        m.train()
        import paddle_tpu.nn.functional as F

        losses = []
        # enough steps that convergence is robust to benign numeric
        # perturbations (4 steps of b4 Adam + train-mode BN is chaotic:
        # a 1e-9 grad difference flipped the old assertion)
        for _ in range(12):
            loss = F.cross_entropy(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert np.isfinite(losses).all() and min(losses[-3:]) < losses[0]
