"""Vision model zoo forward/train smoke (reference:
test/legacy_test vision model tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision.models import (alexnet, mobilenet_v2, resnet18,
                                      vgg11)


@pytest.mark.parametrize("ctor,kwargs,n_params", [
    (alexnet, {}, 57_044_810),
    (vgg11, {}, 128_807_306),
    (vgg11, {"batch_norm": True}, 128_812_810),
    (mobilenet_v2, {}, 2_236_682),
])
def test_forward_shapes_and_param_counts(ctor, kwargs, n_params):
    paddle.seed(0)
    m = ctor(num_classes=10, **kwargs)
    m.eval()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 3, 64, 64).astype(np.float32))
    out = m(x)
    assert out.shape == [2, 10]
    total = sum(int(np.prod(p.shape)) for p in m.parameters())
    assert total == n_params  # matches the reference architectures


def test_mobilenet_trains_a_step():
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    m = mobilenet_v2(scale=0.35, num_classes=4)
    opt = paddle.optimizer.SGD(0.01, parameters=m.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 3, 32, 32).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (4,)))
    out = m(x)
    loss = F.cross_entropy(out, y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    assert np.isfinite(float(loss.numpy()))


def test_pretrained_raises():
    with pytest.raises(NotImplementedError):
        alexnet(pretrained=True)
    # resnet baseline unchanged
    paddle.seed(0)
    r = resnet18(num_classes=10)
    assert len(r.parameters()) > 0


def test_flops_and_summary():
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    f = paddle.flops(net, [1, 1, 28, 28])
    assert f == 347_560  # conv + linear MACs of LeNet at 28x28
    s = paddle.summary(net)
    assert s["total_params"] == 61_610
    assert s["trainable_params"] == 61_610
