"""Taped-backward vjp-trace cache (ops/dispatch.py).

The reference's eager AD amortizes per-op backward setup with codegen'd
GradNodes (paddle/fluid/eager/auto_code_generator/generator/eager_gen.py);
we amortize by jitting the (primals, residuals) forward and the
residual->cotangent backward per (op, static kwargs, input avals).
These tests pin the cache's semantics: hits after two sightings,
numerically identical grads, per-call-closure randomness NEVER frozen,
aval-keyed separation, and graceful fallback for concrete-value traces.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.ops import dispatch


@pytest.fixture(autouse=True)
def _fresh_cache():
    dispatch._VJP_CACHE.clear()
    dispatch._VJP_SEEN.clear()
    dispatch._VJP_BLOCK.clear()
    yield


def _grad_of(fn, x_np):
    x = paddle.to_tensor(x_np, stop_gradient=False)
    y = fn(x)
    y.sum().backward()
    return y.numpy(), x.grad.numpy()


class TestVjpCache:
    def test_cache_hit_after_two_sightings_same_grads(self):
        # count only the tanh entries (empty static kwargs): sum() in the
        # loss reduction is nowadays admissible too and shares the cache
        def n_tanh_entries():
            return len([k for k in dispatch._VJP_CACHE if k[1] == ()])

        x_np = np.linspace(-2, 2, 12).astype(np.float32)
        y0, g0 = _grad_of(paddle.tanh, x_np)      # sighting 1: uncached
        assert n_tanh_entries() == 0
        y1, g1 = _grad_of(paddle.tanh, x_np)      # sighting 2: builds
        assert n_tanh_entries() == 1
        y2, g2 = _grad_of(paddle.tanh, x_np)      # hit: jitted fwd+bwd
        np.testing.assert_allclose(y2, y0, rtol=1e-6)
        np.testing.assert_allclose(g2, g0, rtol=1e-6)
        np.testing.assert_allclose(g2, 1 - np.tanh(x_np) ** 2, rtol=1e-5)

    def test_avals_key_separates_shapes_and_dtypes(self):
        for shape in ((4,), (2, 3), (4,)):
            _grad_of(paddle.exp, np.ones(shape, np.float32))
            _grad_of(paddle.exp, np.ones(shape, np.float32))
        _grad_of(paddle.exp, np.ones((4,), np.float64))
        _grad_of(paddle.exp, np.ones((4,), np.float64))
        # exp entries carry empty static kwargs; the sum() reduction in
        # the loss is separately admissible and must not be counted
        keys = [k for k in dispatch._VJP_CACHE if k[1] == ()]
        assert len(keys) == 3  # (4,) f32, (2,3) f32, (4,) f64

    def test_static_kwargs_in_key(self):
        x_np = np.random.RandomState(0).randn(3, 5).astype(np.float32)
        for ax in (0, 1, 0):
            _, g = _grad_of(lambda t, a=ax: F.softmax(t, axis=a), x_np)
            _, g = _grad_of(lambda t, a=ax: F.softmax(t, axis=a), x_np)
        # softmax grads sum to zero along the softmax axis
        assert abs(g.sum(axis=0)).max() < 1e-5

    def test_dropout_randomness_never_frozen(self):
        paddle.seed(0)
        x = paddle.to_tensor(np.ones((64,), np.float32),
                             stop_gradient=False)
        masks = set()
        for _ in range(6):
            y = F.dropout(x, p=0.5, training=True)
            masks.add(tuple((y.numpy() != 0).tolist()))
        # fresh mask (fresh closure) every call: caching must not bake it
        assert len(masks) >= 4

    def test_multi_output_op_cached(self):
        x_np = np.random.RandomState(1).randn(8).astype(np.float32)
        for _ in range(3):
            x = paddle.to_tensor(x_np, stop_gradient=False)
            vals, idx = paddle.topk(x, k=3)
            vals.sum().backward()
            g = x.grad.numpy()
        expect = np.zeros(8, np.float32)
        expect[np.argsort(x_np)[-3:]] = 1.0
        np.testing.assert_allclose(g, expect)

    def test_tape_then_optimizer_converges_through_cache(self):
        paddle.seed(0)
        import paddle_tpu.nn as nn

        net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
        opt = paddle.optimizer.SGD(0.3, parameters=net.parameters())
        rng = np.random.RandomState(0)
        xs = rng.randn(32, 4).astype(np.float32)
        ys = (xs @ rng.randn(4, 1)).astype(np.float32)
        losses = []
        for _ in range(60):
            pred = net(paddle.to_tensor(xs))
            loss = F.mse_loss(pred, paddle.to_tensor(ys))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])
        assert len(dispatch._VJP_CACHE) > 0  # the loop ran on the cache

    def test_unhashable_static_kwargs_fall_back(self):
        # pad takes a list kwarg -> unhashable key -> plain vjp, no entry
        x = paddle.to_tensor(np.ones((3, 3), np.float32),
                             stop_gradient=False)
        for _ in range(3):
            y = F.pad(x, [1, 1, 1, 1])
            y.sum().backward()
            x.clear_grad()
        assert np.isfinite(y.numpy()).all()

    def test_double_grad_still_works(self):
        # create_graph replays the primal recipe (engine._apply_node),
        # independent of the cached vjp — pin that composition
        for _ in range(3):
            x = paddle.to_tensor(np.array([1.5], np.float32),
                                 stop_gradient=False)
            y = x * x * x
            (g,) = paddle.grad(y, x, create_graph=True)
            (gg,) = paddle.grad(g, x)
            np.testing.assert_allclose(gg.numpy(), [6 * 1.5], rtol=1e-5)
