"""HLO-level verification of the ZeRO / TP sharding claims (VERDICT
round 1: sharding-spec asserts existed but nothing checked the lowered
collectives). These tests lower compiled programs and assert the
expected XLA collectives appear (or don't)."""
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet


def _init(dp=2, mp=1, sharding=4):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        **strategy.hybrid_configs,
        "dp_degree": dp, "mp_degree": mp, "pp_degree": 1,
        "sharding_degree": sharding, "sep_degree": 1,
    }
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _loss(logits, labels):
    return ((logits - labels) ** 2).mean()


def _lower_train_step(step, inputs, labels):
    """One source of truth for the arg build: TrainStep.lower_hlo."""
    return step.lower_hlo(inputs, labels)


class TestZeroStage2:
    def test_grads_reduce_scatter_in_hlo(self):
        hcg = _init(dp=2, sharding=4)
        mesh = hcg.mesh
        paddle.seed(0)
        model = nn.Linear(16, 16)
        opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
        from paddle_tpu.distributed.fleet.meta_parallel.sharding \
            .sharding_optimizer import _stage2_annotate

        _stage2_annotate(opt, hcg)
        assert opt._grad_shard is not None

        step = paddle.jit.TrainStep(model, _loss, opt)
        # batch sharded over both data axes (dp + sharding), the
        # reference's sharding group IS a data-parallel group
        pls = [dist.Replicate()] * mesh.ndim
        pls[mesh.dim_names.index("dp")] = dist.Shard(0)
        pls[mesh.dim_names.index("sharding")] = dist.Shard(0)
        x = dist.shard_tensor(paddle.to_tensor(
            np.random.RandomState(0).randn(16, 16).astype("float32")),
            mesh, pls)
        y = dist.shard_tensor(paddle.to_tensor(
            np.random.RandomState(1).randn(16, 16).astype("float32")),
            mesh, pls)
        txt = _lower_train_step(step, [x], [y])
        # TPU lowers the pattern to a fused reduce-scatter; the CPU
        # backend keeps the canonical all-reduce + dynamic-slice pair
        # (same semantics, no ReduceScatterCreator pass) — accept both
        fused = "reduce-scatter" in txt
        canonical = any("dynamic-slice" in ln and "all-reduce" in ln
                        for ln in txt.splitlines())
        assert fused or canonical, \
            "stage-2 grad sync must lower to reduce-scatter (or its " \
            "all-reduce+dynamic-slice canonical form)"
        # and run it for real
        loss = step([x], [y])
        assert np.isfinite(float(loss.numpy()))
        # states sharded over the sharding axis
        p0 = [p for p in model.parameters() if p._data.ndim == 2][0]
        st = opt._accumulators[id(p0)]
        spec = st["moment1"].sharding.spec
        assert "sharding" in str(spec)


class TestZeroStage3:
    def test_param_all_gather_on_use(self):
        hcg = _init(dp=2, sharding=4)
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(16, 32), nn.GELU(),
                              nn.Linear(32, 16))
        from paddle_tpu.distributed.fleet.meta_parallel.sharding \
            .sharding_optimizer import shard_parameters

        shard_parameters(model, hcg)
        w = model[0].weight
        assert w._dist_attr is not None
        assert not w._data.sharding.is_fully_replicated

        def fwd(arrs, x):
            from paddle_tpu.jit.static_function import _SwappedState
            from paddle_tpu.core import engine
            from paddle_tpu.core.tensor import Tensor

            ps = [p for _, p in model.named_parameters()]
            with _SwappedState(ps, list(arrs)), engine.no_grad():
                return model(Tensor(x))._data

        ps = [p._data for _, p in model.named_parameters()]
        x = jnp.zeros((8, 16), jnp.float32)
        jitted = jax.jit(fwd)
        lowered = jitted.lower(ps, x)
        txt = lowered.compile().as_text()
        # params must ENTER the program sharded (stored sharded in HBM —
        # the ZeRO-3 memory win) ...
        assert all(not p.sharding.is_fully_replicated for p in ps
                   if p.ndim == 2)
        # ... and the forward must materialize the replicated-equivalent
        # compute via a collective: XLA picks all-gather (gather-on-use)
        # or partial-matmul + all-reduce depending on which is cheaper
        assert ("all-gather" in txt) or ("all-reduce" in txt), \
            "ZeRO-3 forward must gather params on use (or compute " \
            "partial matmuls + all-reduce)"

    def test_non_divisible_warns_and_falls_back(self):
        hcg = _init(dp=2, sharding=4)
        paddle.seed(0)
        # dim0=3 not divisible by 4, dim1=8 divisible → shard dim 1
        model = nn.Linear(3, 8)
        from paddle_tpu.distributed.fleet.meta_parallel.sharding \
            .sharding_optimizer import shard_parameters

        shard_parameters(model, hcg)
        assert not model.weight._data.sharding.is_fully_replicated
        # nothing divisible → warning, stays replicated
        model2 = nn.Linear(3, 5)
        with pytest.warns(UserWarning, match="no dimension divisible"):
            shard_parameters(model2, hcg)


class TestTensorParallelHLO:
    def test_row_parallel_psum_in_hlo(self):
        hcg = _init(dp=2, mp=4, sharding=1)
        mesh = hcg.mesh
        paddle.seed(0)
        from paddle_tpu.distributed.fleet.meta_parallel import (
            RowParallelLinear)

        layer = RowParallelLinear(16, 8, input_is_parallel=False,
                                  has_bias=True)

        def fwd(w, b, x):
            from paddle_tpu.jit.static_function import _SwappedState
            from paddle_tpu.core import engine
            from paddle_tpu.core.tensor import Tensor

            with _SwappedState([layer.weight, layer.bias], [w, b]), \
                    engine.no_grad():
                return layer(Tensor(x))._data

        x = jnp.zeros((4, 16), jnp.float32)
        jitted = jax.jit(fwd)
        txt = jitted.lower(layer.weight._data, layer.bias._data,
                           x).compile().as_text()
        assert "all-reduce" in txt, \
            "RowParallelLinear must psum partial outputs over mp"

    def test_parallel_cross_entropy_no_vocab_gather(self):
        hcg = _init(dp=2, mp=4, sharding=1)
        mesh = hcg.mesh
        paddle.seed(0)
        vocab = 64
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ParallelCrossEntropy)

        pce = ParallelCrossEntropy()
        pls = [dist.Replicate()] * mesh.ndim
        pls[mesh.dim_names.index("mp")] = dist.Shard(1)  # vocab dim

        def fwd(logits, labels):
            from paddle_tpu.core import engine
            from paddle_tpu.core.tensor import Tensor

            with engine.no_grad():
                out = pce(Tensor(logits), Tensor(labels))
            return out._data

        logits = dist.shard_tensor(paddle.to_tensor(
            np.random.RandomState(0).randn(8, vocab).astype("float32")),
            mesh, pls)
        labels = paddle.to_tensor(
            np.random.RandomState(1).randint(0, vocab, (8, 1)))
        jitted = jax.jit(fwd)
        txt = jitted.lower(logits._data, labels._data).compile().as_text()
        # per-shard max/sum + mp all-reduce, but NO all-gather of the
        # full vocab-width logits
        gathers = [ln for ln in txt.splitlines() if "all-gather" in ln]
        vocab_gathers = [ln for ln in gathers
                         if re.search(rf"\b{vocab}\b", ln)]
        assert not vocab_gathers, vocab_gathers
        # numerics match the unsharded computation
        out = jitted(logits._data, labels._data)
        ref = F.cross_entropy(
            paddle.to_tensor(np.asarray(logits._data)),
            paddle.to_tensor(np.asarray(labels._data)),
            reduction="none")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref._data),
                                   rtol=1e-5, atol=1e-6)
