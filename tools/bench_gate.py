"""CI gate over BENCH_*/OPBENCH_* telemetry blocks.

``tools/op_bench.py --compare`` gates op latencies; this gates the
RUNTIME-TELEMETRY side of two bench JSONs — the counters/histograms
that explain WHY a number moved (retrace storms, cache-hit-rate
collapse, compile-time blowups, roofline regressions):

    python tools/bench_gate.py BENCH_r05.json BENCH_r06.json
    python tools/bench_gate.py --tol 0.2 OPBENCH_r05.json OPBENCH_r06.json
    python tools/bench_gate.py --metrics jit.trace vjp_cache_hit_rate A B

Exits nonzero when any gated metric regressed by more than ``--tol``
(default 10%) between the two files. Direction is metric-aware:

- count-like metrics (``jit.trace``, ``vjp_cache.miss``, compile-time
  histogram avgs) regress UP;
- rate/utilization metrics (``vjp_cache_hit_rate``, ``roofline.mfu``,
  ``roofline.bw_util``) regress DOWN.

Telemetry blocks are discovered anywhere in the JSON under keys named
``telemetry`` / ``*_telemetry`` (bench.py nests one per rung;
op_bench.py keeps one at top level) and same-named blocks are compared
pairwise. The document ROOT is additionally treated as a block so the
serving rungs' top-level scalars (``decode_a8w8_tokens_per_sec``,
``decode_*_pct_of_hbm_roofline``, ...) gate too.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["gate", "extract_telemetry", "main"]

#: metric -> direction ("up" = an increase is a regression, "down" = a
#: decrease is). The default gate set; extend via --metrics.
DEFAULT_METRICS: Dict[str, str] = {
    # a growing trace count across rounds with the same workload is a
    # retrace storm
    "jit.trace": "up",
    "vjp_cache.miss": "up",
    "vjp_cache.uncacheable": "up",
    "vjp_cache.blocklisted": "up",
    # the no-grad compiled-forward fast path (ops/dispatch.py): growing
    # misses/blocklistings under the same workload mean ops fell off the
    # fast path (a closure crept back in, or statics went unhashable)
    "fwd_cache.miss": "up",
    "fwd_cache.uncacheable": "up",
    "fwd_cache.blocklisted": "up",
    # cache effectiveness / device utilization must not collapse
    "vjp_cache_hit_rate": "down",
    "fwd_cache_hit_rate": "down",
    "roofline.mfu": "down",
    "roofline.bw_util": "down",
    # compile-time histograms gate on their mean
    "compile.vjp_trace_us": "up",
    "compile.vjp_build_us": "up",
    "compile.fwd_trace_us": "up",
    "compile.jit_build_us": "up",
    # serving decode rungs: top-level scalars of the bench JSON (the
    # gate compares the document root as its own block) — throughput
    # and %-of-roofline regress DOWN
    "decode_tokens_per_sec": "down",
    "decode_pct_of_hbm_roofline": "down",
    "decode_int8_tokens_per_sec": "down",
    "decode_int8_pct_of_hbm_roofline": "down",
    "decode_a8w8_tokens_per_sec": "down",
    "decode_a8w8_pct_of_hbm_roofline": "down",
    # grouped bf16 weight-stream decode (r6 tentpole rung): both the
    # throughput and its %-of-weight-roofline must not collapse — the
    # roofline % is the honest one (it normalizes out batch/geometry)
    "decode_bf16_grouped_tokens_per_sec": "down",
    "decode_bf16_grouped_pct_of_hbm_roofline": "down",
    "decode_int8kv_b64_tokens_per_sec": "down",
    # tensor-parallel serving rungs (ISSUE 10, mp2 canonical): the
    # mp-sharded decode/serve throughput regresses DOWN like its mp1
    # siblings — whose unchanged keys above ARE the mp1-throughput-
    # preserved check (TP must not slow the single-chip path)
    "decode_tp2_tokens_per_sec": "down",
    "decode_tp2_pct_of_hbm_roofline": "down",
    "serve_tp2_tokens_per_sec": "down",
    "serve_tp2_p50_ttft_ms": "up",
    "serve_tp2_p99_ttft_ms": "up",
    "serve_tp2_p50_tpot_ms": "up",
    "serve_tp2_goodput": "down",
    # serving-frontend SLO rungs (tools/serve_bench.py): latency
    # percentiles regress UP, delivered throughput DOWN
    "serve_p50_ttft_ms": "up",
    "serve_p99_ttft_ms": "up",
    "serve_p50_tpot_ms": "up",
    "serve_tokens_per_sec": "down",
    # SLO goodput (fraction of finished requests meeting both the
    # TTFT and TPOT targets): both the bench's whole-run scalar and
    # the slo.goodput rolling telemetry gauge regress DOWN
    "serve_goodput": "down",
    "slo.goodput": "down",
    # speculative-decoding rungs (ISSUE 12): delivered throughput and
    # the draft accept rate regress DOWN (a drafter/verify regression
    # shows in accept rate before it shows in tokens/s), TTFT UP like
    # its non-speculative sibling; decode_spec_* is the engine-level
    # acceptance-ceiling rung (bench.py --decode-spec)
    "serve_spec_tokens_per_sec": "down",
    "serve_spec_accept_rate": "down",
    "serve_spec_p50_ttft_ms": "up",
    "serve_spec_p99_ttft_ms": "up",
    "serve_spec_goodput": "down",
    "decode_spec_tokens_per_sec": "down",
    "decode_spec_accept_rate": "down",
    "decode_spec_vs_plain": "down",
    # varlen / long-context attention rungs (ISSUE 13): the packed
    # block-skipping kernel's throughput regresses DOWN and its
    # compiled-program peak bytes UP (the O(T·d) memory pin — a
    # regression back toward the dense path shows here first); the
    # long-context serving rung gates like its short-mix sibling
    "attn_varlen_tokens_per_sec": "down",
    "attn_varlen_peak_bytes": "up",
    "serve_long_p50_ttft_ms": "up",
    "serve_long_p99_ttft_ms": "up",
    "serve_long_p50_tpot_ms": "up",
    "serve_long_tokens_per_sec": "down",
    "serve_long_goodput": "down",
    # chaos-hardened serving rungs (tools/serve_bench.py --chaos,
    # ISSUE 11): survivor token parity is binary and must stay 1.0,
    # chaos goodput/throughput regress DOWN like their fault-free
    # siblings, and request errors under the SAME seeded fault
    # schedule regress UP (more requests dying per injected fault =
    # the isolation got leakier)
    "serve_chaos_survivor_parity": "down",
    "serve_chaos_goodput": "down",
    "serve_chaos_tokens_per_sec": "down",
    "serve_chaos_request_errors": "up",
    # fleet serving rungs (tools/serve_bench.py --fleet, ISSUE 14):
    # routed goodput/throughput regress DOWN and latency UP like the
    # single-replica serve_* siblings; failovers/hedges in the
    # FAULT-FREE fleet run regress UP (any appearing = replicas are
    # falsely suspected/dying under clean load); under the seeded
    # chaos schedule survivor parity is binary (must stay 1.0), lost
    # requests regress UP (the zero-loss failover pin), and chaos
    # goodput/throughput regress DOWN
    "fleet_goodput": "down",
    "fleet_tokens_per_sec": "down",
    "fleet_p50_ttft_ms": "up",
    "fleet_p99_ttft_ms": "up",
    "fleet_failovers": "up",
    "fleet_hedges": "up",
    "fleet_chaos_survivor_parity": "down",
    "fleet_chaos_lost": "up",
    "fleet_chaos_request_errors": "up",
    "fleet_chaos_goodput": "down",
    "fleet_chaos_tokens_per_sec": "down",
    # MoE rungs (ISSUE 15): no-drop train/decode throughput and the
    # activated-FLOPs MFU regress DOWN; moe.dropped_tokens (inside the
    # rung telemetry) regresses UP with NO noise floor — the rung runs
    # in no-drop mode, so a single dropped token is a broken ragged
    # path, not jitter (strict-compared like the lint counters)
    "moe_train_tokens_per_sec": "down",
    "moe_train_mfu": "down",
    "moe_decode_tokens_per_sec": "down",
    "moe.dropped_tokens": "up",
    # static-analysis state the numbers were measured under: the
    # finding count must only go DOWN between rounds, so any growth
    # regresses (direction "up" = an increase fails the gate); gates
    # both the lint.findings counter inside telemetry blocks and a
    # top-level lint_findings scalar
    "lint.findings": "up",
    "lint_findings": "up",
    # continuous telemetry (ISSUE 16): the serving-time attribution's
    # host-overhead residual regresses UP (bookkeeping creep the
    # phase split exists to expose), and alert_fired regresses UP
    # with NO noise floor — the measured rung is a healthy steady
    # state, so a run that starts firing alerts is a regression
    # however small the count (strict-compared like lint)
    "serve_step_host_overhead_ms": "up",
    "alert_fired": "up",
    "alert.fired": "up",
    # batched multi-LoRA serving rungs (tools/serve_bench.py
    # --adapters, ISSUE 18): delivered multi-adapter throughput and
    # its ratio to the single-tenant baseline regress DOWN (the ratio
    # is the honest one — it cancels host noise and pins the grouped
    # delta launch staying ONE kernel however many adapters the chunk
    # mixes); TTFT UP like the plain serve_* siblings; the compiled
    # decode-program count regresses UP (programs scaling with the
    # adapter set is a retrace leak however small)
    "serve_lora_tokens_per_sec": "down",
    "serve_lora_pct_of_single_tenant": "down",
    "serve_lora_p50_ttft_ms": "up",
    "serve_lora_p99_ttft_ms": "up",
    "serve_lora_goodput": "down",
    "serve_lora_decode_programs": "up",
    # per-tenant usage metering (ISSUE 17): one tenant's share of
    # attributed device time regresses UP (a hog crowding out the
    # rest of the mix), and usage_unattributed_ms regresses UP with
    # NO noise floor — device time the ledger failed to attribute is
    # an accounting leak however small (strict-compared like lint)
    "serve_tenant_max_share": "up",
    "usage_unattributed_ms": "up",
    # collective-overlap rungs (ISSUE 19): the ring-overlapped mp2
    # decode and the double-buffered ep2 MoE decode regress DOWN like
    # their blocking-psum siblings (overlap that stops paying shows
    # here first); migration-concurrent drain: decode tokens delivered
    # DURING the drain window regress DOWN (the overlap eroding back
    # toward stop-the-world), per-step join stall UP, and lost
    # requests UP with NO noise floor — a single request dropped by an
    # async migration is a broken re-home, not jitter
    "decode_tp2_overlap_tokens_per_sec": "down",
    "decode_tp2_overlap_pct_of_hbm_roofline": "down",
    "moe_decode_ep2_overlap_tokens_per_sec": "down",
    "fleet_async_migration_decode_tokens": "down",
    "fleet_async_migration_stall_ms": "up",
    "fleet_async_migration_lost": "up",
    # disaggregated prefill/decode fleet + tiered KV (ISSUE 20): the
    # role-split fleet's TTFT tail regresses UP and its goodput /
    # throughput DOWN like every serve sibling; lost requests UP with
    # NO noise floor (a handoff that drops a request is a broken
    # re-home); handoffs regress DOWN — the rung's workload is built
    # to stream them, so a run with fewer is the prefill fleet
    # stalling its hand-offs, not jitter
    "serve_disagg_p50_ttft_ms": "up",
    "serve_disagg_p99_ttft_ms": "up",
    "serve_disagg_tokens_per_sec": "down",
    "serve_disagg_goodput": "down",
    "serve_disagg_lost": "up",
    "serve_disagg_handoffs": "down",
}

#: absolute-change floors so tiny counts/latencies don't trip the
#: relative gate on noise
_ABS_FLOOR_COUNT = 3.0
_ABS_FLOOR_US = 10.0


def extract_telemetry(doc: dict, prefix: str = "") -> Dict[str, dict]:
    """Every telemetry block in the JSON, keyed by its path — bench.py
    emits ``telemetry`` and ``decode_telemetry``, op_bench.py a
    top-level ``telemetry``."""
    out: Dict[str, dict] = {}
    if not isinstance(doc, dict):
        return out
    for k, v in doc.items():
        path = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            if k == "telemetry" or k.endswith("_telemetry"):
                out[path] = v
            else:
                out.update(extract_telemetry(v, path))
    return out


def _scalar_blocks(doc: dict, metrics: Dict[str, str],
                   prefix: str = "") -> Dict[str, dict]:
    """Dicts anywhere in the JSON that carry a gated metric as a direct
    scalar key (bench.py's serving rungs live at the document root, or
    under a ``parsed`` wrapper in archived BENCH_r*.json files)."""
    out: Dict[str, dict] = {}
    if not isinstance(doc, dict):
        return out
    if any(isinstance(doc.get(m), (int, float)) for m in metrics):
        out[prefix or "<root>"] = doc
    for k, v in doc.items():
        if isinstance(v, dict) and k != "telemetry" \
                and not k.endswith("_telemetry"):
            out.update(_scalar_blocks(
                v, metrics, f"{prefix}.{k}" if prefix else k))
    return out


def _metric_value(block: dict, name: str) -> Optional[float]:
    """Find ``name`` in a telemetry block: counters, gauges, top-level
    scalars (vjp_cache_hit_rate), or histogram means."""
    for section in ("counters", "gauges"):
        v = block.get(section, {}).get(name)
        if v is not None:
            return float(v)
    v = block.get(name)
    if isinstance(v, (int, float)):
        return float(v)
    h = block.get("histograms", {}).get(name)
    if isinstance(h, dict) and h.get("count"):
        return float(h.get("avg", 0.0))
    return None


def _regressed(name: str, direction: str, prev: float, cur: float,
               tol: float) -> bool:
    if name.startswith(("lint", "alert", "usage")) \
            or name in ("moe.dropped_tokens",
                        "fleet_async_migration_lost",
                        "serve_disagg_lost"):
        # lint findings, alert fires, unattributed device time,
        # no-drop-mode dropped tokens, and requests lost across an
        # async migration must only go down between rounds — ANY
        # growth regresses, no noise floor (a single new finding /
        # alert / unattributed ms / dropped token / lost request is a
        # real defect, not measurement jitter)
        return cur > prev if direction == "up" else cur < prev
    floor = _ABS_FLOOR_US if name.endswith("_us") else _ABS_FLOOR_COUNT
    if direction == "up":
        return cur > max(prev * (1 + tol), prev + floor)
    # "down": rates in [0, 1] — relative drop with a small abs floor
    return cur < min(prev * (1 - tol), prev - 0.01)


def gate(prev_doc: dict, cur_doc: dict,
         metrics: Optional[Dict[str, str]] = None,
         tol: float = 0.10) -> Tuple[List[str], int]:
    """(regression lines, #compared). Same-path telemetry blocks are
    compared metric-by-metric; blocks present on only one side are
    skipped (a new rung is not a regression)."""
    metrics = metrics or DEFAULT_METRICS
    prev_blocks = extract_telemetry(prev_doc)
    cur_blocks = extract_telemetry(cur_doc)
    # scalar rung metrics (decode_*_tokens_per_sec, *_pct_of_hbm_
    # roofline) live OUTSIDE telemetry blocks — gate the dicts that
    # carry them too, so a throughput collapse fails as loudly
    for name, blk in _scalar_blocks(prev_doc, metrics).items():
        prev_blocks.setdefault(name, blk)
    for name, blk in _scalar_blocks(cur_doc, metrics).items():
        cur_blocks.setdefault(name, blk)
    bad: List[str] = []
    compared = 0
    for path in sorted(set(prev_blocks) & set(cur_blocks)):
        pb, cb = prev_blocks[path], cur_blocks[path]
        for name, direction in metrics.items():
            p, c = _metric_value(pb, name), _metric_value(cb, name)
            if p is None or c is None:
                continue
            compared += 1
            if _regressed(name, direction, p, c, tol):
                arrow = "+" if c > p else "-"
                delta = (100.0 * (c / p - 1.0)) if p else float("inf")
                bad.append(f"{path}:{name}: {p:g} -> {c:g} "
                           f"({arrow}{abs(delta):.0f}%, "
                           f"regress-{direction})")
    return bad, compared


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="gate the telemetry blocks of two BENCH_*/"
                    "OPBENCH_* JSONs (nonzero exit on regression)")
    ap.add_argument("prev", help="previous round's JSON")
    ap.add_argument("cur", help="current round's JSON")
    ap.add_argument("--tol", type=float, default=0.10,
                    help="relative regression tolerance (default 0.10)")
    ap.add_argument("--metrics", nargs="*", default=None,
                    help="explicit metric names to gate (direction "
                         "taken from the default table; unknown names "
                         "gate 'up')")
    args = ap.parse_args(argv)

    with open(args.prev) as f:
        prev_doc = json.load(f)
    with open(args.cur) as f:
        cur_doc = json.load(f)
    metrics = None
    if args.metrics:
        metrics = {m: DEFAULT_METRICS.get(m, "up") for m in args.metrics}
    bad, compared = gate(prev_doc, cur_doc, metrics, args.tol)
    if not compared:
        print("bench_gate: no comparable telemetry metrics found "
              "(missing telemetry blocks?)", file=sys.stderr)
        return 2
    if bad:
        print(f"bench_gate REGRESSIONS (> {100 * args.tol:.0f}%):")
        for line in bad:
            print(" ", line)
        return 1
    print(f"bench_gate: no telemetry regressions "
          f"({compared} metrics compared)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
