"""BERT-rung ablation ladder (VERDICT r4 weak #2 diagnosis).

Times the bert-base pretraining step (bench.py --bert geometry: b32
s512, AMP O2 bf16, whole-step compiled) with one component changed per
mode, in a fresh subprocess each:

    python tools/bert_profile.py --mode full|nodrop|nohead|noce|...

Each mode prints one JSON line {mode, tokens_per_sec, mfu}.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

BATCH, SEQ, STEPS = 32, 512, 8


def run(batch=BATCH, seq=SEQ, dropout=0.1, head="full", ce="full",
        attn_dropout=0.0, fa_blocks=None, moment_dtype="bfloat16"):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.text.models import (BertForPretraining,
                                        BertPretrainingCriterion,
                                        bert_base)

    paddle.seed(0)
    if fa_blocks is not None:
        from paddle_tpu.nn.functional.attention import (
            set_flash_block_sizes)

        set_flash_block_sizes(*fa_blocks)
    model = BertForPretraining(
        bert_base(max_position_embeddings=seq,
                  hidden_dropout_prob=dropout,
                  attention_probs_dropout_prob=attn_dropout))
    if head == "none":
        # knock out the MLM decoder matmul: loss feeds on the transform
        # output's first 2 vocab-ish columns instead
        import jax.numpy as jnp

        import paddle_tpu as pd

        orig_forward = BertForPretraining.forward

        def forward_nohead(self, input_ids, token_type_ids=None,
                           attention_mask=None):
            seq_h, pooled = self.bert(input_ids, token_type_ids,
                                      attention_mask)
            h = self.transform_norm(
                self.transform_act(self.transform(seq_h)))
            b, s, d = h.shape
            vocab = self.decoder_bias.shape[0]
            mlm = pd.zeros([b, s, vocab], dtype=h.dtype) + \
                h[:, :, :1] + self.decoder_bias
            return mlm, self.nsp(pooled)
        BertForPretraining.forward = forward_nohead
    crit = BertPretrainingCriterion()
    if ce == "none":
        class MeanCrit(paddle.nn.Layer):
            def forward(self, mlm_logits, nsp_logits, mlm_labels,
                        nsp_labels):
                return mlm_logits.astype("float32").mean() \
                    + nsp_logits.astype("float32").mean()
        crit = MeanCrit()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                                 weight_decay=0.01,
                                 moment_dtype=moment_dtype)
    model, opt = paddle.amp.decorate(model, opt, level="O2",
                                     dtype="bfloat16")
    step = paddle.jit.TrainStep(model, crit, opt)

    rng = np.random.RandomState(0)
    vocab = 30522
    ids = paddle.to_tensor(rng.randint(0, vocab, (batch, seq)))
    types = paddle.to_tensor(rng.randint(0, 2, (batch, seq)))
    mlm = paddle.to_tensor(np.where(
        rng.rand(batch, seq) < 0.15,
        rng.randint(0, vocab, (batch, seq)), -100))
    nsp = paddle.to_tensor(rng.randint(0, 2, (batch,)))
    args, labels = [ids, types], [mlm, nsp]

    loss = step(args, labels)
    _ = float(loss.numpy())
    t0 = time.perf_counter()
    for _i in range(STEPS):
        loss = step(args, labels)
    final = float(loss.numpy())
    dt = time.perf_counter() - t0
    assert np.isfinite(final)
    n_params = sum(int(np.prod(p.shape))
                   for _n, p in model.named_parameters())
    tps = STEPS * batch * seq / dt
    d_model, n_layers = 768, 12
    fpt = 6 * n_params + 12 * n_layers * seq * d_model
    peak = 197e12
    # cost-model roofline for the compiled step (XLA's flops/bytes, not
    # the 6N+12Lsd estimate) from the same timed window
    rl = step.roofline(dt / STEPS)
    return tps, round(tps * fpt / peak, 4), (rl.as_dict() if rl else None)


MODES = {
    "full": lambda: run(),
    "nodrop": lambda: run(dropout=0.0),
    "nohead": lambda: run(head="none"),
    "noce": lambda: run(ce="none"),
    "nodrop_noce": lambda: run(dropout=0.0, ce="none"),
    "nodrop_nohead": lambda: run(dropout=0.0, head="none"),
    "b48": lambda: run(batch=48),
    "b64": lambda: run(batch=64),
    "nodrop_b64": lambda: run(batch=64, dropout=0.0),
    "fa128": lambda: run(fa_blocks=(128, 128)),
    "fa512": lambda: run(fa_blocks=(512, 512)),
    "attndrop": lambda: run(attn_dropout=None),  # canonical attn dropout
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", required=True, choices=sorted(MODES))
    args = ap.parse_args()
    t0 = time.time()
    tps, mfu, roofline = MODES[args.mode]()
    print(json.dumps({"mode": args.mode, "tokens_per_sec": round(tps, 1),
                      "mfu": mfu, "roofline": roofline,
                      "wall": round(time.time() - t0, 1)}))


if __name__ == "__main__":
    main()
