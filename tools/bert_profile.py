"""BERT-rung ablation ladder (VERDICT r4 weak #2 diagnosis).

Times the bert-base pretraining step (bench.py --bert geometry: b32
s512, AMP O2 bf16, whole-step compiled) with one component changed per
mode, in a fresh subprocess each:

    python tools/bert_profile.py --mode full|nodrop|nohead|noce|...

Each mode prints one JSON line {mode, tokens_per_sec, mfu}.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

BATCH, SEQ, STEPS = 32, 512, 8


def run(batch=BATCH, seq=SEQ, dropout=0.1, head="full", ce="full",
        attn_dropout=0.0, fa_blocks=None, moment_dtype="bfloat16"):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.text.models import (BertForPretraining,
                                        BertPretrainingCriterion,
                                        bert_base)

    paddle.seed(0)
    if fa_blocks is not None:
        from paddle_tpu.nn.functional.attention import (
            set_flash_block_sizes)

        set_flash_block_sizes(*fa_blocks)
    model = BertForPretraining(
        bert_base(max_position_embeddings=seq,
                  hidden_dropout_prob=dropout,
                  attention_probs_dropout_prob=attn_dropout))
    if head == "none":
        # knock out the MLM decoder matmul: loss feeds on the transform
        # output's first 2 vocab-ish columns instead
        import jax.numpy as jnp

        import paddle_tpu as pd

        orig_forward = BertForPretraining.forward

        def forward_nohead(self, input_ids, token_type_ids=None,
                           attention_mask=None):
            seq_h, pooled = self.bert(input_ids, token_type_ids,
                                      attention_mask)
            h = self.transform_norm(
                self.transform_act(self.transform(seq_h)))
            b, s, d = h.shape
            vocab = self.decoder_bias.shape[0]
            mlm = pd.zeros([b, s, vocab], dtype=h.dtype) + \
                h[:, :, :1] + self.decoder_bias
            return mlm, self.nsp(pooled)
        BertForPretraining.forward = forward_nohead
    crit = BertPretrainingCriterion()
    if ce == "none":
        class MeanCrit(paddle.nn.Layer):
            def forward(self, mlm_logits, nsp_logits, mlm_labels,
                        nsp_labels):
                return mlm_logits.astype("float32").mean() \
                    + nsp_logits.astype("float32").mean()
        crit = MeanCrit()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                                 weight_decay=0.01,
                                 moment_dtype=moment_dtype)
    model, opt = paddle.amp.decorate(model, opt, level="O2",
                                     dtype="bfloat16")
    step = paddle.jit.TrainStep(model, crit, opt)

    rng = np.random.RandomState(0)
    vocab = 30522
    ids = paddle.to_tensor(rng.randint(0, vocab, (batch, seq)))
    types = paddle.to_tensor(rng.randint(0, 2, (batch, seq)))
    mlm = paddle.to_tensor(np.where(
        rng.rand(batch, seq) < 0.15,
        rng.randint(0, vocab, (batch, seq)), -100))
    nsp = paddle.to_tensor(rng.randint(0, 2, (batch,)))
    args, labels = [ids, types], [mlm, nsp]

    loss = step(args, labels)
    _ = float(loss.numpy())
    t0 = time.perf_counter()
    for _i in range(STEPS):
        loss = step(args, labels)
    final = float(loss.numpy())
    dt = time.perf_counter() - t0
    assert np.isfinite(final)
    n_params = sum(int(np.prod(p.shape))
                   for _n, p in model.named_parameters())
    tps = STEPS * batch * seq / dt
    d_model, n_layers = 768, 12
    fpt = 6 * n_params + 12 * n_layers * seq * d_model
    peak = 197e12
    # cost-model roofline for the compiled step (XLA's flops/bytes, not
    # the 6N+12Lsd estimate) from the same timed window
    rl = step.roofline(dt / STEPS)
    return tps, round(tps * fpt / peak, 4), (rl.as_dict() if rl else None)


def run_op_table(batch=BATCH, seq=SEQ, iters=10, top=10):
    """Per-op time/roofline table for the BERT rung (VERDICT r5 weak
    #2: the 'd768-trunk-bound' diagnosis behind MFU 0.342 was asserted,
    not proven). Each component op of the b32 s512 bert-base step is
    compiled as its OWN XLA program; flops/bytes come from
    ``compiled.cost_analysis()`` (roofline.program_cost), wall time
    from a synced loop, and the table ranks the top sinks by their
    estimated share of the train step. ``ideal_us`` is the roofline
    floor max(flops/peak_FLOPs, bytes/peak_BW); ``util`` = ideal /
    measured (1.0 = the op sits ON its roofline — no headroom without
    restructuring). ``step_mult`` folds fwd+bwd into the estimate
    (matmuls replay ~2x in backward, elementwise ~1x)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.profiler import roofline

    d, dff, heads, L, V = 768, 3072, 12, 12, 30522
    hd = d // heads
    T = batch * seq
    rng = np.random.RandomState(0)
    bf = jnp.bfloat16

    def arr(*shape):
        return jnp.asarray(rng.randn(*shape).astype(np.float32), bf)

    x = arr(T, d)
    x4 = arr(T, dff)
    qh = arr(batch, seq, heads, hd)
    labels = jnp.asarray(rng.randint(0, V, (T,)), jnp.int32)

    def ce(h, w, lab):
        lg = (h @ w).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(lg, axis=-1)
        picked = jnp.take_along_axis(lg, lab[:, None], axis=-1)[:, 0]
        return jnp.mean(lse - picked)

    # (name, fn, args, calls_per_step, fwd+bwd multiplier)
    ops = [
        ("qkv_proj", lambda a, w: a @ w, (x, arr(d, 3 * d)), L, 3),
        ("attn_flash",
         lambda q, k, v: jax.nn.dot_product_attention(q, k, v),
         (qh, arr(batch, seq, heads, hd), arr(batch, seq, heads, hd)),
         L, 3),
        ("out_proj", lambda a, w: a @ w, (x, arr(d, d)), L, 3),
        ("ffn1", lambda a, w: a @ w, (x, arr(d, dff)), L, 3),
        ("ffn2", lambda a, w: a @ w, (x4, arr(dff, d)), L, 3),
        ("gelu", jax.nn.gelu, (x4,), L, 2),
        ("layer_norm",
         lambda a: (a - jnp.mean(a, -1, keepdims=True))
         * jax.lax.rsqrt(jnp.var(a.astype(jnp.float32), -1,
                                 keepdims=True) + 1e-5).astype(a.dtype),
         (x,), 2 * L, 2),
        ("mlm_head_ce", ce, (x, arr(d, V), labels), 1, 3),
        ("embedding_gather",
         lambda tbl, ids: tbl[ids],
         (arr(V, d), jnp.asarray(rng.randint(0, V, (T,)), jnp.int32)),
         1, 2),
    ]
    peak_f, peak_b = roofline.device_peaks()
    rows = []
    for name, fn, args, calls, mult in ops:
        exe = jax.jit(fn).lower(*args).compile()
        cost = roofline.program_cost(exe) or {"flops": 0.0, "bytes": 0.0}
        out = exe(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = exe(*args)
        jax.block_until_ready(out)
        us = (time.perf_counter() - t0) / iters * 1e6
        ideal_us = max(cost["flops"] / peak_f,
                       cost["bytes"] / peak_b) * 1e6
        rows.append({
            "op": name,
            "flops": cost["flops"],
            "bytes": cost["bytes"],
            "measured_us": round(us, 1),
            "ideal_us": round(ideal_us, 1),
            "util": round(ideal_us / us, 3) if us > 0 else 0.0,
            "calls_per_step": calls,
            "step_mult": mult,
            "est_step_us": round(us * calls * mult, 1),
        })
    rows.sort(key=lambda r: -r["est_step_us"])
    total = sum(r["est_step_us"] for r in rows)
    for r in rows:
        r["est_step_share"] = round(r["est_step_us"] / total, 3) \
            if total else 0.0
    for r in rows[:top]:
        print(f"{r['op']:>18}: {r['measured_us']:>9.1f}us measured | "
              f"{r['ideal_us']:>8.1f}us roofline (util "
              f"{100 * r['util']:.0f}%) | x{r['calls_per_step']} "
              f"calls x{r['step_mult']} fwd+bwd = "
              f"{100 * r['est_step_share']:.1f}% of step",
              file=sys.stderr)
    return {"ops": rows[:top], "est_step_us_total": round(total, 1),
            "peak_flops": peak_f, "peak_hbm_bw": peak_b,
            "batch": batch, "seq": seq}


MODES = {
    "full": lambda: run(),
    "nodrop": lambda: run(dropout=0.0),
    "nohead": lambda: run(head="none"),
    "noce": lambda: run(ce="none"),
    "nodrop_noce": lambda: run(dropout=0.0, ce="none"),
    "nodrop_nohead": lambda: run(dropout=0.0, head="none"),
    "b48": lambda: run(batch=48),
    "b64": lambda: run(batch=64),
    "nodrop_b64": lambda: run(batch=64, dropout=0.0),
    "fa128": lambda: run(fa_blocks=(128, 128)),
    "fa512": lambda: run(fa_blocks=(512, 512)),
    "attndrop": lambda: run(attn_dropout=None),  # canonical attn dropout
    "op_table": run_op_table,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", required=True, choices=sorted(MODES))
    ap.add_argument("--no-lint", action="store_true",
                    help="skip the tpu_lint preflight gate")
    args = ap.parse_args()
    from paddle_tpu.analysis.preflight import preflight

    preflight("bert_profile", no_lint=args.no_lint)
    t0 = time.time()
    if args.mode == "op_table":
        out = run_op_table()
        print(json.dumps({"mode": "op_table", **out,
                          "wall": round(time.time() - t0, 1)}))
        return
    tps, mfu, roofline = MODES[args.mode]()
    print(json.dumps({"mode": args.mode, "tokens_per_sec": round(tps, 1),
                      "mfu": mfu, "roofline": roofline,
                      "wall": round(time.time() - t0, 1)}))


if __name__ == "__main__":
    main()
