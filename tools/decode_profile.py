"""Decode-bottleneck ablation: time isolated components of the 1.3B
paged-KV decode step on the real chip (VERDICT r3 weak #1 diagnosis).

Run one mode per fresh subprocess (HBM fragmentation):
    python tools/decode_profile.py --mode full|noattn|headonly|xla_attn|...

Each mode prints one JSON line with tokens/sec for a 64-step decode
chunk at batch 16 on the gpt3-1.3b geometry (d2048 L24 h16 hd128).
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

D, L, H, HD = 2048, 24, 16, 128
VOCAB = 51200
BATCH = 16
PROMPT = 128
CHUNK = 64
PAGE = 16


def build(bf16_stack=True, bf16_embed=False):
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference import FusedCausalLM

    paddle.seed(0)
    model = FusedCausalLM(vocab_size=VOCAB, embed_dim=D, num_heads=H,
                         dim_feedforward=4 * D, num_layers=L,
                         max_position=PROMPT + CHUNK + 64)
    if bf16_stack:
        st = model.stack
        for n in ("qkv_weight", "qkv_bias", "out_weight", "out_bias",
                  "ffn1_weight", "ffn1_bias", "ffn2_weight", "ffn2_bias"):
            p = getattr(st, n)
            p._rebind(p._data.astype(jnp.bfloat16))
    if bf16_embed:
        model.embed._rebind(model.embed._data.astype(jnp.bfloat16))
    return model


def time_chunk(fn, args, steps=3):
    """Compile + time a chunk program; returns sec/chunk."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    # re-fetch a scalar to force through the tunnel
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    _ = np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    return (time.perf_counter() - t0) / steps


def mode_full(cache_dtype="float32", attn="pallas", bf16_embed=False,
              quant=None):
    """Current engine path end-to-end (greedy, chunk=64)."""
    import jax.numpy as jnp

    from paddle_tpu.inference import GenerationEngine

    model = build(bf16_embed=bf16_embed)
    eng = GenerationEngine(model, page_size=PAGE,
                           max_length=PROMPT + CHUNK + 2,
                           decode_chunk=CHUNK, quant=quant)
    if attn == "xla":
        import paddle_tpu as _p

        # flag (not monkeypatch): decode_raw's fused-stream branch
        # checks the flag and would bypass a patched paged_attention
        _p.set_flags({"paged_attention_backend": "xla"})
    if cache_dtype != "float32":
        from paddle_tpu.inference import kv_cache as kvmod
        orig_init = kvmod.BlockKVCacheManager.__init__

        def patched(self, *a, **kw):
            kw["dtype"] = jnp.bfloat16
            orig_init(self, *a, **kw)
        kvmod.BlockKVCacheManager.__init__ = patched

    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, (BATCH, PROMPT))
    new = 1 + CHUNK
    eng.generate(ids, max_new_tokens=new)  # compile
    t0 = time.perf_counter()
    out = eng.generate(ids, max_new_tokens=new)
    dt = time.perf_counter() - t0
    assert out.shape == (BATCH, PROMPT + new)
    return BATCH * new / dt


def mode_weights_only():
    """Transformer matmuls only (no attention, no cache, no logits):
    the pure weight-streaming floor."""
    import jax
    import jax.numpy as jnp

    model = build()
    st = model.stack
    w = st._stack()

    def chunk(weights, x):
        def tok_step(carry, _):
            h = carry

            def body(h, wl):
                hn = ((h - jnp.mean(h, -1, keepdims=True))
                      * wl["ln1_scale"][:D]).astype(h.dtype)
                qkv = hn @ wl["qkv_weight"]
                att = qkv[:, :D]
                h = (h + att @ wl["out_weight"] + wl["out_bias"]) \
                    .astype(h.dtype)
                ff = jax.nn.gelu(h @ wl["ffn1_weight"] + wl["ffn1_bias"])
                h = (h + ff @ wl["ffn2_weight"] + wl["ffn2_bias"]) \
                    .astype(h.dtype)
                return h, None
            h, _ = jax.lax.scan(body, h, weights)
            return h, h[:, 0]
        h, outs = jax.lax.scan(tok_step, x, jnp.arange(CHUNK))
        return outs

    fn = jax.jit(chunk)
    x = jnp.ones((BATCH, D), jnp.bfloat16)
    sec = time_chunk(fn, (w, x))
    return BATCH * CHUNK / sec


def mode_weights_only_grouped(prefetch=True):
    """GROUPED transformer matmuls only (no attention/cache/logits):
    the r6 fused O+LN2+FFN tail kernel (+ in-tail next-layer QKV when
    ``prefetch``) against mode_weights_only's per-projection floor —
    the delta is the per-call dispatch/ramp-up cost the grouping
    removes. The "attention output" is the QKV projection's leading D
    columns, exactly like mode_weights_only."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.stream_linear import (
        stream_layer_tail, stream_linear)

    model = build()
    st = model.stack
    w = st._stack()
    eps, act = st.epsilon, st.activation

    def qkv_at(weights, l, h):
        ln_s = jax.lax.dynamic_index_in_dim(weights["ln1_scale"], l,
                                            0, False)
        ln_b = jax.lax.dynamic_index_in_dim(weights["ln1_bias"], l,
                                            0, False)
        hn = ((h - jnp.mean(h, -1, keepdims=True)) * ln_s + ln_b) \
            .astype(h.dtype)
        return stream_linear(hn, weights["qkv_weight"], layer=l,
                             bias=weights["qkv_bias"], out_dtype=h.dtype)

    def chunk(weights, x):
        def tok_step(carry, _):
            h = carry

            def body(l, hq):
                h, qkv = hq
                att = qkv[:, :D]
                nq = None
                if prefetch:
                    nq = dict(w=weights["qkv_weight"],
                              b=weights["qkv_bias"],
                              ln_s=weights["ln1_scale"],
                              ln_b=weights["ln1_bias"],
                              layer=jnp.minimum(l + 1, L - 1))
                res = stream_layer_tail(
                    att, h, weights["out_weight"],
                    weights["ffn1_weight"], weights["ffn2_weight"],
                    layer=l, bo=weights["out_bias"],
                    b1=weights["ffn1_bias"], b2=weights["ffn2_bias"],
                    ln2_scale=weights["ln2_scale"],
                    ln2_bias=weights["ln2_bias"], epsilon=eps,
                    activation=act, next_qkv=nq, out_dtype=h.dtype)
                if prefetch:
                    h, qkv = res
                else:
                    h = res
                    qkv = qkv_at(weights, jnp.minimum(l + 1, L - 1), h)
                return h, qkv

            qkv0 = qkv_at(weights, 0, h)
            h, _ = jax.lax.fori_loop(0, L, body, (h, qkv0))
            return h, h[:, 0]
        h, outs = jax.lax.scan(tok_step, x, jnp.arange(CHUNK))
        return outs

    fn = jax.jit(chunk)
    x = jnp.ones((BATCH, D), jnp.bfloat16)
    sec = time_chunk(fn, (w, x))
    return BATCH * CHUNK / sec


def mode_engine_grouped(batch=32, grouped="on", prefetch=True,
                        quant=None):
    """Engine end-to-end with the grouped weight-stream path forced
    on/off (grouped-vs-ungrouped and prefetch on/off ablations)."""
    import paddle_tpu as paddle

    paddle.set_flags({"decode_grouped": grouped,
                      "decode_prefetch": prefetch})
    return mode_engine_full(batch, quant=quant)


def mode_engine_tp(batch=32, mp=2):
    """Engine end-to-end TENSOR-PARALLEL over ``mp`` chips (ISSUE 10):
    per-chip weight streams shrink to 1/mp, two psums per layer ride
    the ICI — compare against engine_grouped_b32 to read the
    collective + split-grouping overhead directly. Needs >= mp
    devices (it is a multi-chip ablation, not an emulation)."""
    import jax

    if len(jax.devices()) < mp:
        raise SystemExit(
            f"engine_tp mp={mp} needs {mp} devices, have "
            f"{len(jax.devices())} — run on a multi-chip host")
    from paddle_tpu.inference import GenerationEngine as _GE

    orig_init = _GE.__init__

    def ginit(self, *a, **kw):
        kw.setdefault("mp_degree", mp)
        orig_init(self, *a, **kw)

    _GE.__init__ = ginit
    try:
        return mode_engine_full(batch)
    finally:
        _GE.__init__ = orig_init


def mode_head_only(bf16=False):
    """Logits head (h @ embed.T) + argmax, 64 steps."""
    import jax
    import jax.numpy as jnp

    model = build(bf16_embed=bf16)
    embed = model.embed._data

    def chunk(embed, h):
        def tok_step(carry, _):
            logits = carry @ embed.T
            tok = jnp.argmax(logits, -1)
            return carry + 1e-6 * tok[:, None].astype(carry.dtype), tok
        _, toks = jax.lax.scan(tok_step, h, jnp.arange(CHUNK))
        return toks

    fn = jax.jit(chunk)
    h = jnp.ones((BATCH, D), embed.dtype)
    sec = time_chunk(fn, (embed, h))
    return BATCH * CHUNK / sec


def mode_cache_copy(dtype="float32"):
    """Cost of shuttling the paged cache through scan xs->ys per token
    (the current decode structure) with NO compute."""
    import jax
    import jax.numpy as jnp

    pages_per_seq = -(-(PROMPT + CHUNK + 2) // PAGE)
    npages = BATCH * pages_per_seq + 1
    shape = (L, H, npages, PAGE, HD)
    dt = jnp.float32 if dtype == "float32" else jnp.bfloat16
    ck, cv = jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    def chunk(ck, cv):
        def tok_step(carry, i):
            ck, cv = carry

            def body(_, per_layer):
                k, v = per_layer
                k = k.at[0, 0, 0, 0].add(1.0)
                return 0.0, (k, v)
            _, (ck, cv) = jax.lax.scan(body, 0.0, (ck, cv))
            return (ck, cv), ck[0, 0, 0, 0, 0]
        (ck, cv), outs = jax.lax.scan(tok_step, (ck, cv),
                                      jnp.arange(CHUNK))
        return outs

    # no donation: time_chunk re-invokes with the same arrays
    fn = jax.jit(chunk)
    sec = time_chunk(fn, (ck, cv))
    return BATCH * CHUNK / sec


def mode_pallas_attn(dtype="float32"):
    """Pallas paged-attention kernel alone, 64 steps x 24 layers."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.paged_attention import paged_attention

    pages_per_seq = -(-(PROMPT + CHUNK + 2) // PAGE)
    npages = BATCH * pages_per_seq + 1
    dt = jnp.float32 if dtype == "float32" else jnp.bfloat16
    # PAGE-MAJOR head-major pool (r5 layout): [P, n_kv, ps, d]
    ck = jnp.zeros((npages, H, PAGE, HD), dt)
    cv = jnp.zeros((npages, H, PAGE, HD), dt)
    tables = jnp.arange(1, 1 + BATCH * pages_per_seq, dtype=jnp.int32) \
        .reshape(BATCH, pages_per_seq)
    lens = jnp.full((BATCH,), PROMPT, jnp.int32)

    def chunk(q, ck, cv):
        def tok_step(q, i):
            def body(q, _):
                o = paged_attention(q, ck, cv, lens, tables)
                return o.astype(q.dtype), None
            q, _ = jax.lax.scan(body, q, jnp.arange(L))
            return q, q[0, 0, 0]
        q, _ = jax.lax.scan(tok_step, q, jnp.arange(CHUNK))
        return q

    q = jnp.ones((BATCH, H, HD), dt)
    fn = jax.jit(chunk)
    sec = time_chunk(fn, (q, ck, cv))
    return BATCH * CHUNK / sec


def mode_carry_cache(dtype="float32"):
    """In-place alternative to the scan xs->ys shuttle: cache pool as
    fori_loop carry, one scatter per layer (layers folded into the page
    dim). If XLA aliases the carry, cost ~= true bytes written (tiny)."""
    import jax
    import jax.numpy as jnp

    pages_per_seq = -(-(PROMPT + CHUNK + 2) // PAGE)
    npages = BATCH * pages_per_seq + 1
    dt = jnp.float32 if dtype == "float32" else jnp.bfloat16
    shape = (L * npages, H, PAGE, HD)  # page-major head-major (r5)
    ck, cv = jnp.zeros(shape, dt), jnp.zeros(shape, dt)
    tables = jnp.arange(1, 1 + BATCH * pages_per_seq, dtype=jnp.int32) \
        .reshape(BATCH, pages_per_seq)

    def chunk(ck, cv):
        def tok_step(carry, i):
            ck, cv = carry
            pos = jnp.full((BATCH,), PROMPT, jnp.int32) + i
            page_ids = tables[jnp.arange(BATCH), pos // PAGE]
            slots = pos % PAGE
            newk = jnp.ones((BATCH, H, HD), dt)

            def body(l, c):
                ck, cv = c
                pid = page_ids + l * npages
                ck = ck.at[pid, :, slots].set(newk)
                cv = cv.at[pid, :, slots].set(newk)
                return (ck, cv)
            ck, cv = jax.lax.fori_loop(0, L, body, (ck, cv))
            return (ck, cv), ck[0, 0, 0, 0]
        (ck, cv), outs = jax.lax.scan(tok_step, (ck, cv),
                                      jnp.arange(CHUNK))
        return outs

    fn = jax.jit(chunk)
    sec = time_chunk(fn, (ck, cv))
    return BATCH * CHUNK / sec


def mode_head_variant(kind):
    """Logits-head alternatives (head_only fp32 = 7.3ms/step is 17x off
    the 420MB/819GB/s roofline; bf16 untransposed is pathological)."""
    import jax
    import jax.numpy as jnp

    model = build()
    embed = model.embed._data  # [V, D] fp32
    # derive the variant from the kind string: transpose iff t_,
    # bf16-cast iff bf16, preferred fp32 accumulate iff prefer
    w = jnp.array(embed.T) if kind.startswith("t_") else embed
    if "bf16" in kind:
        w = w.astype(jnp.bfloat16)
    prefer = "prefer" in kind
    argmax = "noargmax" not in kind

    cdim = 0 if kind.startswith("t_") else 1

    def chunk(w, h):
        def tok_step(carry, _):
            logits = jax.lax.dot_general(
                carry, w, (((1,), (cdim,)), ((), ())),
                preferred_element_type=jnp.float32 if prefer else None)
            tok = (jnp.argmax(logits, -1) if argmax
                   else jnp.max(logits, -1).astype(jnp.int32))
            return carry + (1e-6 * tok[:, None]).astype(carry.dtype), tok
        _, toks = jax.lax.scan(tok_step, h, jnp.arange(CHUNK))
        return toks

    fn = jax.jit(chunk)
    h = jnp.ones((BATCH, D), jnp.bfloat16 if "bf16" in kind
                 else jnp.float32)
    sec = time_chunk(fn, (w, h))
    return BATCH * CHUNK / sec


def mode_argmax_only():
    """Isolate argmax over [b, V] inside a scan (head matmul excluded)."""
    import jax
    import jax.numpy as jnp

    logits = jnp.ones((BATCH, VOCAB), jnp.float32)

    def chunk(logits, h):
        def tok_step(carry, _):
            tok = jnp.argmax(logits + carry[:, :1], -1)
            return carry + (1e-6 * tok[:, None]).astype(carry.dtype), tok
        _, toks = jax.lax.scan(tok_step, h, jnp.arange(CHUNK))
        return toks

    fn = jax.jit(chunk)
    h = jnp.ones((BATCH, VOCAB), jnp.float32)
    sec = time_chunk(fn, (logits, h))
    return BATCH * CHUNK / sec


def mode_weights_unrolled():
    """Weight streaming with UNSTACKED per-layer weights and a Python-
    unrolled layer loop (no scan slice-copies of the stacked arrays)."""
    import jax
    import jax.numpy as jnp

    model = build()
    w = model.stack._stack()
    layers = [{k: v[l] for k, v in w.items()} for l in range(L)]

    def chunk(layers, x):
        def tok_step(h, _):
            for wl in layers:
                hn = ((h - jnp.mean(h, -1, keepdims=True))
                      * wl["ln1_scale"]).astype(h.dtype)
                qkv = hn @ wl["qkv_weight"]
                att = qkv[:, :D]
                h = (h + att @ wl["out_weight"] + wl["out_bias"]) \
                    .astype(h.dtype)
                ff = jax.nn.gelu(h @ wl["ffn1_weight"] + wl["ffn1_bias"])
                h = (h + ff @ wl["ffn2_weight"] + wl["ffn2_bias"]) \
                    .astype(h.dtype)
            return h, h[:, 0]
        h, outs = jax.lax.scan(tok_step, x, jnp.arange(CHUNK))
        return outs

    fn = jax.jit(chunk)
    x = jnp.ones((BATCH, D), jnp.bfloat16)
    sec = time_chunk(fn, (layers, x))
    return BATCH * CHUNK / sec


def mode_loop_overhead():
    """Pure lax.scan iteration cost: 64 steps of h+1 on [b, d]."""
    import jax
    import jax.numpy as jnp

    def chunk(h):
        def tok_step(carry, _):
            return carry + 1.0, carry[0, 0]
        h, outs = jax.lax.scan(tok_step, h, jnp.arange(CHUNK))
        return outs

    fn = jax.jit(chunk)
    h = jnp.ones((BATCH, D), jnp.bfloat16)
    sec = time_chunk(fn, (h,))
    return BATCH * CHUNK / sec


def mode_head_noloop():
    """ONE head matmul+argmax per device program (no scan): per-
    dispatch+compute latency through the tunnel."""
    import jax
    import jax.numpy as jnp

    model = build()
    w = jnp.array(model.embed._data.T).astype(jnp.bfloat16)

    def one(w, h):
        logits = jax.lax.dot_general(
            h, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return jnp.argmax(logits, -1)

    fn = jax.jit(one)
    h = jnp.ones((BATCH, D), jnp.bfloat16)
    out = fn(w, h)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(CHUNK):
        out = fn(w, out.sum() * jnp.zeros((BATCH, D), jnp.bfloat16)
                 + h)
    _ = np.asarray(out)[:1]
    sec = time.perf_counter() - t0
    return BATCH * CHUNK / sec


def mode_head_indep():
    """64-scan of the head matmul with NO loop-carried dependence on the
    matmul input (tests cross-iteration pipelining/prefetch)."""
    import jax
    import jax.numpy as jnp

    model = build()
    w = jnp.array(model.embed._data.T).astype(jnp.bfloat16)

    def chunk(w, h):
        def tok_step(acc, _):
            logits = jax.lax.dot_general(
                h, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return acc + jnp.argmax(logits, -1).sum(), acc
        acc, outs = jax.lax.scan(tok_step, jnp.int32(0),
                                 jnp.arange(CHUNK))
        return acc

    fn = jax.jit(chunk)
    h = jnp.ones((BATCH, D), jnp.bfloat16)
    sec = time_chunk(fn, (w, h))
    return BATCH * CHUNK / sec


def mode_head_unroll():
    """16 sequential head matmul+argmax steps UNROLLED in one jit (no
    while loop): is lax.scan itself the bottleneck?"""
    import jax
    import jax.numpy as jnp

    model = build()
    w = jnp.array(model.embed._data.T).astype(jnp.bfloat16)
    k = 16

    def prog(w, h):
        toks = []
        for _ in range(k):
            logits = jax.lax.dot_general(
                h, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            tok = jnp.argmax(logits, -1)
            toks.append(tok)
            h = h + (1e-6 * tok[:, None]).astype(h.dtype)
        return jnp.stack(toks)

    fn = jax.jit(prog)
    h = jnp.ones((BATCH, D), jnp.bfloat16)
    sec = time_chunk(fn, (w, h))
    return BATCH * k / sec


def mode_weights_int8():
    """Weight streaming with int8 weights dequantized in-body (bytes
    halve vs bf16; if bandwidth-bound, time should halve)."""
    import jax
    import jax.numpy as jnp

    model = build()
    w = model.stack._stack()
    q = {k: (jnp.round(v * 127).astype(jnp.int8) if v.ndim == 3
             else v) for k, v in w.items()}

    def chunk(weights, x):
        def tok_step(carry, _):
            h = carry

            def body(h, wl):
                hn = ((h - jnp.mean(h, -1, keepdims=True))
                      * wl["ln1_scale"]).astype(h.dtype)
                qkv = hn @ (wl["qkv_weight"].astype(jnp.bfloat16)
                            * (1.0 / 127))
                att = qkv[:, :D]
                h = (h + att @ (wl["out_weight"].astype(jnp.bfloat16)
                                * (1.0 / 127)) + wl["out_bias"]) \
                    .astype(h.dtype)
                ff = jax.nn.gelu(
                    h @ (wl["ffn1_weight"].astype(jnp.bfloat16)
                         * (1.0 / 127)) + wl["ffn1_bias"])
                h = (h + ff @ (wl["ffn2_weight"].astype(jnp.bfloat16)
                               * (1.0 / 127)) + wl["ffn2_bias"]) \
                    .astype(h.dtype)
                return h, None
            h, _ = jax.lax.scan(body, h, weights)
            return h, h[:, 0]
        h, outs = jax.lax.scan(tok_step, x, jnp.arange(CHUNK))
        return outs

    fn = jax.jit(chunk)
    x = jnp.ones((BATCH, D), jnp.bfloat16)
    sec = time_chunk(fn, (q, x))
    return BATCH * CHUNK / sec


def mode_xla_paged_attn(batch=32, dtype="bfloat16"):
    """Current XLA gather attention over the FOLDED pool, isolated:
    64-step scan x 24 layers at the given batch."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.paged_attention import _xla_paged

    pages_per_seq = -(-(PROMPT + CHUNK + 2) // PAGE)
    npages = batch * pages_per_seq + 1
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    # PAGE-MAJOR head-major pool (r5 layout): [P, n_kv, ps, d]
    ck = jnp.zeros((L * npages, H, PAGE, HD), dt)
    cv = jnp.zeros((L * npages, H, PAGE, HD), dt)
    tables = jnp.arange(1, 1 + batch * pages_per_seq, dtype=jnp.int32) \
        .reshape(batch, pages_per_seq)
    lens = jnp.full((batch,), PROMPT, jnp.int32)

    def chunk(q, ck, cv):
        def tok_step(q, i):
            def body(l, qq):
                o = _xla_paged(qq, ck, cv, lens, tables + l * npages)
                return o.astype(qq.dtype)
            q = jax.lax.fori_loop(0, L, body, q)
            return q, q[0, 0, 0]
        q, _ = jax.lax.scan(tok_step, q, jnp.arange(CHUNK))
        return q

    q = jnp.ones((batch, H, HD), dt)
    fn = jax.jit(chunk)
    sec = time_chunk(fn, (q, ck, cv))
    return batch * CHUNK / sec


def mode_engine_full(batch=32, backend=None, quant=None, kv=None):
    """Current engine end-to-end at the given batch (bf16 stack; the
    engine derives bf16 compute + bf16 KV from the weight dtype).
    backend forces FLAGS_paged_attention_backend; quant='int8' runs
    weight-only int8 (the bench's int8 rung), quant='a8w8' the full
    dynamic-activation int8 x int8 matmul path; kv='int8' additionally
    quantizes the KV cache (cache-KV int8 mode)."""
    import paddle_tpu as paddle

    if backend:
        paddle.set_flags({"paged_attention_backend": backend})
    if kv == "int8":
        from paddle_tpu.inference import GenerationEngine as _GE
        orig_ginit = _GE.__init__

        def ginit(self, *a, **kw):
            kw.setdefault("kv_dtype", "int8")
            orig_ginit(self, *a, **kw)
        _GE.__init__ = ginit
    global BATCH
    old, BATCH = BATCH, batch
    try:
        return mode_full(quant=quant)
    finally:
        BATCH = old


def mode_stream_attn(batch=32, dtype="bfloat16"):
    """Pool-streaming Pallas attention isolated over the folded pool:
    64-step scan x 24 layers at the given batch (compare
    xla_paged_attn_b32 — same traffic, no gather materialization)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.paged_attention import (
        _stream_paged, build_pool_ownership)

    pages_per_seq = -(-(PROMPT + CHUNK + 2) // PAGE)
    chunk_pages = max(1, 1024 // PAGE)
    npages = -(-(batch * pages_per_seq + 1) // chunk_pages) * chunk_pages
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    ck = jnp.zeros((L * npages, H, PAGE, HD), dt)
    cv = jnp.zeros((L * npages, H, PAGE, HD), dt)
    tables = jnp.arange(1, 1 + batch * pages_per_seq, dtype=jnp.int32) \
        .reshape(batch, pages_per_seq)
    lens = jnp.full((batch,), PROMPT, jnp.int32)

    def chunk(q, ck, cv):
        own = build_pool_ownership(tables, lens, npages, PAGE)

        def tok_step(q, i):
            def body(l, qq):
                o = _stream_paged(qq, ck, cv, lens, tables,
                                  pool_base=l * npages,
                                  pool_pages=npages, ownership=own)
                return o.astype(qq.dtype)
            q = jax.lax.fori_loop(0, L, body, q)
            return q, q[0, 0, 0]
        q, _ = jax.lax.scan(tok_step, q, jnp.arange(CHUNK))
        return q

    q = jnp.ones((batch, H, HD), dt)
    fn = jax.jit(chunk)
    sec = time_chunk(fn, (q, ck, cv))
    return batch * CHUNK / sec


def mode_engine_knockout(batch=32, knock="attn", quant=None):
    """Engine end-to-end with ONE component knocked out in place —
    in-context component cost = full minus knockout."""
    import jax.numpy as jnp

    import paddle_tpu.incubate.nn.fused_transformer as ft
    from paddle_tpu.inference import GenerationEngine

    if quant == "int8":
        orig_build = globals()["build"]

        def build_q(*a, **kw):
            model = orig_build(*a, **kw)
            model.stack.quantize_weight_only_int8()
            return model
        globals()["build"] = build_q

    if knock == "attn":
        def fake_attn(q, ck, cv, lens, tables, **kw):
            return q  # [b, n_q, d] passthrough, no KV read
        ft.paged_attention = fake_attn

        def fake_fused(q, nk, nv, ck, cv, lens, tables, **kw):
            return q, ck, cv
        ft.paged_decode_attention_inplace = fake_fused
    elif knock == "head":
        def fake_logits(self, h, head_t, lnf_s, lnf_b):
            b = h.shape[0]
            return jnp.broadcast_to(h[:, :1].astype(jnp.float32),
                                    (b, VOCAB))
        GenerationEngine._logits = fake_logits
    elif knock == "argmax":
        @staticmethod
        def fake_pick(logits, key, sample_cfg):
            return jnp.zeros((logits.shape[0],), jnp.int32)
        GenerationEngine._pick_token = fake_pick
    elif knock == "scatter":
        def fake_write(ck, cv, k, v, pos, tables):
            return ck, cv
        ft.write_kv_pages = fake_write
    try:
        return _with_batch(batch, mode_full)
    finally:
        if quant == "int8":
            globals()["build"] = orig_build


def _with_batch(batch, fn):
    global BATCH
    old, BATCH = BATCH, batch
    try:
        return fn()
    finally:
        BATCH = old


def mode_pallas_page(page, dtype="bfloat16"):
    """Pallas paged attention with a different page size (DMA width)."""
    global PAGE
    old, PAGE = PAGE, page
    try:
        return mode_pallas_attn(dtype)
    finally:
        PAGE = old


MODES = {
    "full": lambda: mode_full(),
    "bf16cache": lambda: mode_full(cache_dtype="bfloat16"),
    "bf16embed": lambda: mode_full(bf16_embed=True),
    "bf16both": lambda: mode_full(cache_dtype="bfloat16", bf16_embed=True),
    "xla_attn": lambda: mode_full(attn="xla"),
    "weights_only": mode_weights_only,
    "head_only": lambda: mode_head_only(False),
    "head_only_bf16": lambda: mode_head_only(True),
    "cache_copy": lambda: mode_cache_copy("float32"),
    "cache_copy_bf16": lambda: mode_cache_copy("bfloat16"),
    "pallas_attn": lambda: mode_pallas_attn("float32"),
    "pallas_attn_bf16": lambda: mode_pallas_attn("bfloat16"),
    "carry_cache": lambda: mode_carry_cache("float32"),
    "carry_cache_bf16": lambda: mode_carry_cache("bfloat16"),
    "head_t_bf16": lambda: mode_head_variant("t_bf16"),
    "head_t_bf16_prefer": lambda: mode_head_variant("t_bf16_prefer"),
    "head_bf16_prefer": lambda: mode_head_variant("bf16_prefer"),
    "head_t_f32": lambda: mode_head_variant("t_f32"),
    "pallas_page32": lambda: mode_pallas_page(32),
    "pallas_page64": lambda: mode_pallas_page(64),
    "pallas_page8": lambda: mode_pallas_page(8),
    "head_t_bf16_noargmax": lambda: mode_head_variant("t_bf16_noargmax"),
    "head_bf16_prefer_noargmax":
        lambda: mode_head_variant("bf16_prefer_noargmax"),
    "argmax_only": mode_argmax_only,
    "weights_unrolled": mode_weights_unrolled,
    "loop_overhead": mode_loop_overhead,
    "head_noloop": mode_head_noloop,
    "head_indep": mode_head_indep,
    "head_unroll": mode_head_unroll,
    "weights_int8": mode_weights_int8,
    "xla_paged_attn_b32": lambda: mode_xla_paged_attn(32),
    "xla_paged_attn_b16": lambda: mode_xla_paged_attn(16),
    "stream_attn_b32": lambda: mode_stream_attn(32),
    "stream_attn_b64": lambda: mode_stream_attn(64),
    "weights_only_b32": lambda: _with_batch(32, mode_weights_only),
    "weights_unrolled_b32": lambda: _with_batch(32, mode_weights_unrolled),
    "weights_int8_b32": lambda: _with_batch(32, mode_weights_int8),
    "engine_b32": lambda: mode_engine_full(32),
    "engine_stream_b32": lambda: mode_engine_full(32, backend="stream"),
    "engine_stream_b64": lambda: mode_engine_full(64, backend="stream"),
    "engine_xla_b64": lambda: mode_engine_full(64, backend="xla"),
    "engine_int8_b32": lambda: mode_engine_full(32, quant="int8"),
    "engine_kv8_b32": lambda: mode_engine_full(32, kv="int8"),
    "engine_int8kv8_b32":
        lambda: mode_engine_full(32, quant="int8", kv="int8"),
    "engine_int8kv8_b64":
        lambda: mode_engine_full(64, quant="int8", kv="int8"),
    "engine_int8_stream_b32":
        lambda: mode_engine_full(32, backend="stream", quant="int8"),
    # A8W8 ablation rows: dynamic-act int8 x int8 matmuls vs the
    # weight-only rungs above (same geometry — the delta IS the
    # activation-dequant round the a8w8 kernel removes)
    "engine_a8w8_b32": lambda: mode_engine_full(32, quant="a8w8"),
    "engine_a8w8_b64": lambda: mode_engine_full(64, quant="a8w8"),
    "engine_a8w8kv8_b32":
        lambda: mode_engine_full(32, quant="a8w8", kv="int8"),
    "engine_a8w8kv8_b64":
        lambda: mode_engine_full(64, quant="a8w8", kv="int8"),
    # grouped weight-stream rows (r6): kernel floor, grouped-vs-
    # ungrouped engine delta, and the cross-layer-prefetch knockout
    "weights_only_grouped": mode_weights_only_grouped,
    "weights_only_grouped_b32":
        lambda: _with_batch(32, mode_weights_only_grouped),
    "weights_only_grouped_noprefetch_b32":
        lambda: _with_batch(32,
                            lambda: mode_weights_only_grouped(False)),
    "engine_grouped_b32": lambda: mode_engine_grouped(32),
    "engine_ungrouped_b32":
        lambda: mode_engine_grouped(32, grouped="off"),
    "prefetch_on": lambda: mode_engine_grouped(32, prefetch=True),
    "prefetch_off": lambda: mode_engine_grouped(32, prefetch=False),
    "engine_grouped_int8_b32":
        lambda: mode_engine_grouped(32, quant="int8"),
    # tensor-parallel ablation (ISSUE 10): mp2-sharded engine vs the
    # single-chip grouped row — the delta is the per-layer psum pair
    # plus the tail grouping split at the collective boundaries
    "engine_grouped_mp2_b32": lambda: mode_engine_tp(32, mp=2),
    "engine_int8_noattn_b32":
        lambda: mode_engine_knockout(32, "attn", quant="int8"),
    "engine_int8_nohead_b32":
        lambda: mode_engine_knockout(32, "head", quant="int8"),
    "engine_noattn_b32": lambda: mode_engine_knockout(32, "attn"),
    "engine_nohead_b32": lambda: mode_engine_knockout(32, "head"),
    "engine_noargmax_b32": lambda: mode_engine_knockout(32, "argmax"),
    "engine_noscatter_b32": lambda: mode_engine_knockout(32, "scatter"),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", required=True, choices=sorted(MODES))
    ap.add_argument("--no-lint", action="store_true",
                    help="skip the tpu_lint preflight gate")
    args = ap.parse_args()
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.analysis.preflight import preflight

    preflight("decode_profile", no_lint=args.no_lint)
    t0 = time.time()
    tps = MODES[args.mode]()
    out = {"mode": args.mode, "tokens_per_sec": round(tps, 1),
           "wall": round(time.time() - t0, 1)}
    # engine-path modes record each compiled program's XLA cost model
    # and the synced per-chunk wall time (profiler/roofline.py): attach
    # the achieved-rate table so an ablation shows WHERE on the roofline
    # each variant lands, not just tokens/sec
    from paddle_tpu.profiler import roofline

    rl = roofline.report()
    if rl:
        out["roofline"] = rl
    print(json.dumps(out))


if __name__ == "__main__":
    main()
