"""Isolate the BERT MLM head matmul cost (fwd+bwd): [T, d] x [d, V]
with T=16384, d=768, V=30522 bf16 — the bert_profile nohead ablation
measured ~61ms/step (13% MFU); this locates the slow matmul form.

    python tools/head_bench.py --form ty|pre_t|f32acc|untied
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

T, D, V = 16384, 768, 30522


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--form", default="ty",
                    choices=["ty", "pre_t", "f32acc", "untied"])
    args = ap.parse_args()
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(T, D).astype(np.float32)).astype(jnp.bfloat16)
    w = jnp.asarray(rng.randn(V, D).astype(np.float32)).astype(jnp.bfloat16)
    wt = jnp.asarray(np.ascontiguousarray(
        rng.randn(D, V).astype(np.float32))).astype(jnp.bfloat16)

    if args.form == "ty":
        # BertForPretraining form: matmul(h, w, transpose_y=True)
        def f(h, w):
            lg = jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())))
            return (lg * 1e-6).sum()
        grad = jax.jit(jax.value_and_grad(f, argnums=(0, 1)))
        arg2 = w
    elif args.form == "pre_t":
        def f(h, wt):
            lg = jax.lax.dot_general(h, wt, (((1,), (0,)), ((), ())))
            return (lg * 1e-6).sum()
        grad = jax.jit(jax.value_and_grad(f, argnums=(0, 1)))
        arg2 = wt
    elif args.form == "f32acc":
        def f(h, w):
            lg = jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            return (lg * 1e-6).sum()
        grad = jax.jit(jax.value_and_grad(f, argnums=(0, 1)))
        arg2 = w
    else:  # untied: fwd only
        def f(h, w):
            lg = jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())))
            return (lg * 1e-6).sum()
        grad = jax.jit(jax.value_and_grad(f, argnums=(0,)))
        arg2 = w

    out = grad(h, arg2)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(5):
        out = grad(h, arg2)
    _ = np.asarray(out[0])
    dt = (time.perf_counter() - t0) / 5
    flops = (6 if args.form != "untied" else 4) * T * D * V
    print(json.dumps({"form": args.form, "ms": round(dt * 1e3, 2),
                      "tflops": round(flops / dt / 1e12, 1)}))


if __name__ == "__main__":
    main()
