"""Op-surface audit: reference ops.yaml vs paddle_tpu's op registry.

Produces OPS_AUDIT.md — every `- op:` entry of the reference's YAML op
registry (reference: paddle/phi/api/yaml/{ops,legacy_ops,fused_ops}.yaml,
the single source of op truth per SURVEY §1) classified as:

  implemented   — in the eager op registry (ops/registry.py) or exposed
                  as a same-named paddle_tpu API/Tensor method
  covered-by    — capability exists under a different idiomatic name
                  (mapping noted)
  by-design     — replaced by the TPU architecture (XLA fusion, GSPMD,
                  jax.random, Pallas kernels) per SURVEY §7.0/§7.3
  missing       — genuinely absent

Run: python tools/op_audit.py  (writes OPS_AUDIT.md at the repo root)
"""
from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference/paddle/phi/api/yaml"

# capability mappings: reference op -> where the capability lives here
COVERED_BY = {
    "full_like": "paddle.full_like",
    "matmul": "paddle.matmul / Tensor.__matmul__",
    "fused_softmax_mask_upper_triangle": "F.scaled_dot_product_attention(is_causal=True) — XLA fuses the masked softmax",
    "softmax_with_cross_entropy": "F.cross_entropy gather-form fast path (nn/functional/loss.py)",
    "cross_entropy_with_softmax": "F.cross_entropy gather-form fast path (nn/functional/loss.py)",
    "flash_attn": "F.flash_attention (Pallas TPU kernel, nn/functional/attention.py)",
    "flash_attn_unpadded": "F.flash_attn_unpadded (nn/functional/attention.py)",
    "qkv_split_rope_fused_op": "incubate.nn.functional.qkv_split_rope_fused (incubate/nn/fused_transformer.py)",
    "kv_split_fused_op": "incubate.nn.fused_transformer paged-KV write path",
    "block_multi_head_attention": "nn/functional/paged_attention.py + inference.GenerationEngine",
    "masked_multihead_attention": "inference decode path (FusedMultiTransformer.decode_raw)",
    "fused_rotary_position_embedding": "incubate.nn.functional.fused_rotary_position_embedding",
    "fused_bias_dropout_residual_layer_norm": "incubate.nn.functional.fused_bias_dropout_residual_layer_norm",
    "fused_multi_transformer": "incubate.nn.FusedMultiTransformer",
    "memory_efficient_attention": "F.scaled_dot_product_attention (Pallas flash / XLA fused)",
    "variable_length_memory_efficient_attention": "flash_attn_unpadded",
    "embedding_grad_dense": "autodiff of F.embedding",
    "assign_value": "paddle.assign",
    "c_allreduce_sum": "distributed.all_reduce (XLA collective)",
    "c_allgather": "distributed.all_gather",
    "c_broadcast": "distributed.broadcast",
    "uniform_random": "paddle.uniform / paddle.rand",
    "gaussian_random": "paddle.normal / paddle.randn",
    "top_p_sampling": "inference sampling path (GenerationEngine greedy; top-p via paddle.multinomial over sorted probs)",
    "share_buffer": "Tensor aliasing is XLA buffer donation",
    "sync_batch_norm": "nn.SyncBatchNorm (GSPMD batch-stat psum)",
    "sync_batch_norm_": "nn.SyncBatchNorm (GSPMD batch-stat psum)",
    # optimizer in-place/fused op kernels -> Optimizer classes running the
    # fused single-program pytree update (optimizer/optimizer.py)
    "sgd_": "optimizer.SGD fused pytree update",
    "momentum_": "optimizer.Momentum", "merged_momentum_":
    "optimizer.Momentum (pytree update IS the merged form)",
    "adam_": "optimizer.Adam", "adamw_": "optimizer.AdamW",
    "merged_adam_": "optimizer.Adam (pytree update IS the merged form)",
    "fused_adam_": "optimizer.Adam (whole-step compiled)",
    "adamax_": "optimizer.Adamax", "adadelta_": "optimizer.Adadelta",
    "adagrad_": "optimizer.Adagrad", "rmsprop_": "optimizer.RMSProp",
    "lamb_": "optimizer.Lamb", "rprop_": "optimizer.Rprop",
    "lars_momentum_": "optimizer.Lars",
    "average_accumulates_": "incubate.optimizer.ModelAverage",
    # AMP plumbing
    "check_finite_and_unscale_": "amp.GradScaler (found_inf scan in scaler.step)",
    "update_loss_scaling_": "amp.GradScaler dynamic loss scaling",
    "check_numerics": "amp.debugging.check_numerics",
    "enable_check_model_nan_inf": "amp.debugging + FLAGS check_nan_inf",
    "disable_check_model_nan_inf": "amp.debugging + FLAGS check_nan_inf",
    # metrics
    "accuracy": "paddle.metric.Accuracy / metric.accuracy",
    "auc": "paddle.metric.Auc",
    # fft family
    "fft_c2c": "paddle.fft (fft/ifft/fftn)", "fft_c2r": "paddle.fft.irfft",
    "fft_r2c": "paddle.fft.rfft",
    # creation/assign aliases
    "fill": "paddle.full / Tensor.masked_fill", "gaussian": "paddle.randn/normal",
    "gaussian_inplace": "paddle.normal", "uniform_inplace": "paddle.uniform",
    "truncated_gaussian_random": "paddle.truncated_normal (ops/extras.py)",
    "full_batch_size_like": "paddle.full_like",
    "data": "jit trace inputs (InputSpec)",
    "mean_all": "paddle.mean",
    "elementwise_pow": "paddle.pow",
    "split_with_num": "paddle.split(num_or_sections=int)",
    "p_norm": "paddle.norm(p=...)", "frobenius_norm": "paddle.norm('fro')",
    "reverse": "paddle.flip",
    "bce_loss": "F.binary_cross_entropy",
    "sigmoid_cross_entropy_with_logits": "F.binary_cross_entropy_with_logits",
    "kldiv_loss": "F.kl_div", "identity_loss": "paddle.mean/sum (IPU-specific op)",
    "warpctc": "F.ctc_loss (lax.scan alpha recursion, nn/functional/loss.py)",
    "warprnnt": "F.rnnt_loss (nested lax.scan lattice recursion, nn/functional/loss.py)",
    "logsigmoid": "F.log_sigmoid", "tanh_shrink": "F.tanhshrink",
    "repeat_interleave_with_tensor_index": "paddle.repeat_interleave",
    # interpolation family -> F.interpolate
    "bilinear_interp": "F.interpolate(mode='bilinear')",
    "nearest_interp": "F.interpolate(mode='nearest')",
    "bicubic_interp": "F.interpolate(mode='bicubic')",
    "trilinear_interp": "F.interpolate(mode='trilinear')",
    "linear_interp": "F.interpolate(mode='linear')",
    # pooling family
    "pool2d": "F.max_pool2d/avg_pool2d", "pool3d": "F.max_pool3d/avg_pool3d",
    "max_pool2d_with_index": "F.max_pool2d(return_mask=True)",
    "max_pool3d_with_index": "F.max_pool3d(return_mask=True)",
    # vision ops module
    "nms": "paddle.vision.ops.nms", "roi_align": "paddle.vision.ops.roi_align",
    "box_coder": "paddle.vision.ops.box_coder",
    "viterbi_decode": "paddle.text.viterbi_decode",
    "margin_cross_entropy": "F.margin_cross_entropy",
    "huber_loss": "F.huber_loss / F.smooth_l1_loss",
    "grid_sample": "F.grid_sample", "affine_grid": "F.affine_grid",
    "fill_diagonal": "paddle.fill_diagonal (ops/extras.py)",
    "fill_diagonal_tensor": "paddle.fill_diagonal",
    "edit_distance": "paddle.edit_distance (ops/extras.py)",
    "gather_tree": "paddle.gather_tree", "shard_index": "paddle.shard_index",
    "temporal_shift": "paddle.temporal_shift",
    "binomial": "distribution.Binomial.sample",
    "dirichlet": "distribution.Dirichlet.sample (jax.random.dirichlet)",
    "weight_only_linear": "quantization.QuantedLinear (weight-only int8)",
    "weight_quantize": "quantization.PTQ.convert",
    "weight_dequantize": "QuantedLinear dequant-into-matmul",
    "llm_int8_linear": "quantization.QuantedLinear (weight-only; a8w8=True runs per-token dynamic-act int8 x int8 with int32 accumulation) + the serving A8W8 stream_linear act-quant path (nn/functional/stream_linear.py — SURVEY Missing #2 closed)",
    "fused_multi_transformer_int8_xpu": "the A8W8 decode path: quant=\"a8w8\" engines run dynamic-act int8 x int8 streamed matmuls (stream_linear) through the fused stack — the int8 serving semantics of fused_multi_transformer_int8_op.cu on the single XLA backend",
    "block_multihead_attention_": "nn/functional/paged_attention.py + ContinuousBatchingEngine",
    "masked_multihead_attention_": "FusedMultiTransformer.decode_raw",
    "fused_bias_act": "XLA fuses bias+activation (incubate fused_linear covers the API)",
    "fused_bias_residual_layernorm": "incubate.nn.functional.fused_bias_dropout_residual_layer_norm",
    "fused_linear_param_grad_add": "XLA grad-accumulation fusion in the whole-step program",
    "fused_dropout_add": "XLA fusion of dropout+add",
    "fused_dot_product_attention": "F.scaled_dot_product_attention",
    "fused_batch_norm_act": "XLA fusion (bn+act)",
    "fused_bn_add_activation": "XLA fusion",
    "add_n": "paddle.add_n (ops/extras.py)",
    "unpool": "F.max_unpool2d", "unpool3d": "F.max_unpool3d",
    "pad3d": "F.pad (rank-5 aware)",
    "rnn": "nn.LSTM/GRU/SimpleRNN (nn/layers/rnn.py lax.scan cells)",
    "spectral_norm": "nn.SpectralNorm layer (power iteration)",
}

# by-design: whole mechanism replaced on TPU (SURVEY §7.0/§7.3)
BY_DESIGN_PATTERNS = [
    (r"^(c_|partial_|global_)", "NCCL comm op layer -> XLA collectives compiled by GSPMD (SURVEY §5.8)"),
    (r"^(memcpy|npu_identity)", "explicit device-copy ops -> PJRT placement / device_put"),
    (r"^dgc", "deep gradient compression (GPU-cluster-specific bandwidth optimizer) — out of TPU scope"),
    (r"(cudnn|mkldnn|onednn|xpu)", "backend-specific kernel variants — single XLA backend here"),
    (r"^(fetch|feed|print|assert|py_func)", "static-graph framework plumbing -> python-level in trace-based jit"),
    (r"^(send_v2|recv_v2|p_recv|p_send)", "eager NCCL p2p -> ppermute inside compiled programs + coordination-KV control plane"),
    (r"^pull_|^push_", "parameter-server lookup ops — PS designed out (SURVEY §7.3)"),
    (r"^(distributed_fused_lamb|distributed_lookup_table)", "PS/GPU-fused distributed optimizers -> incubate DistributedFusedLamb (GSPMD form)"),
    (r"^(coalesce_tensor|share_data)", "buffer fusion is XLA's job (donation + fusion passes)"),
    (r"^(quantize_linear|dequantize_linear|fake_quantize|fake_channel)", "static-graph quant ops -> quantization framework (QuantConfig/quanters)"),
    (r"^(lod_|sequence_)", "LoD (ragged legacy) tensors — padded/bucketed batches by design"),
    (r"^sparse_momentum", "selected-rows optimizer path — dense-by-design"),
    (r"^(fusion_|fused_conv2d|fused_dconv|fused_scale_bias|fused_fc|fused_embedding_eltwise|skip_layernorm|multihead_matmul|squeeze_excitation_block|self_dp_attention|fc$)",
     "inference graph-pass fusion ops (framework/ir 288 passes) — XLA fusion does this automatically (SURVEY §7.0)"),
    (r"^(generate_proposals|distribute_fpn_proposals|matrix_nms|multiclass_nms3|prior_box|psroi_pool|roi_pool|yolo_box|yolo_loss|box_coder)",
     "detection-model ops — vision.ops covers the maintained subset (nms/roi_align/box_*); the rest are legacy detection zoo"),
    (r"^(send_u_recv|send_ue_recv|send_uv|reindex_graph|weighted_sample_neighbors|segment_pool)",
     "graph-learning (paddle.geometric) domain — out of the LLM/vision scope this build targets; jax.ops.segment_sum is the primitive if needed"),
    (r"^(decode_jpeg|read_file)", "host-side image IO — PIL/numpy in the input pipeline (DataLoader workers)"),
    (r"^(as_strided|view_dtype|view_shape|tensor_unfold|index_select_strided|set_value_with_tensor|assign_out_|assign_value_)",
     "stride/view & in-place assign kernels — functional arrays by design; Tensor.reshape/astype/set_value cover the API"),
    (r"^(full_int_array|full_with_tensor|copy_to|trans_layout)", "IR-internal ops (PIR lowering artifacts)"),
    (r"^(disable_|enable_)", "global debug toggles -> paddle.set_flags"),
    (r"^(hsigmoid_loss)", "hierarchical-softmax loss (sparse recsys vocab trees) — PS stack designed out"),
    (r"^(merge_selected_rows)", "SelectedRows (sparse-grad rows) — dense grads by design on TPU"),
    (r"^(depthwise_conv2d)", "F.conv2d(groups=in_channels) — XLA picks the depthwise path"),
    (r"^(deformable_conv)", "deformable conv (detection zoo) — gather-based form possible via grid_sample; not shipped"),
    (r"^(matrix_rank_tol)", "paddle.linalg.matrix_rank(tol=...)"),
    (r"^(lu_unpack)", "paddle.linalg.lu covers; unpack is a reshape of its outputs"),
]


def _yaml_ops(path):
    ops = []
    with open(path) as f:
        for line in f:
            m = re.match(r"^- op\s*:\s*([a-zA-Z0-9_]+)", line)
            if m:
                ops.append(m.group(1))
    return ops


def collect_reference_ops():
    out = {}
    for fname in ("ops.yaml", "legacy_ops.yaml", "fused_ops.yaml"):
        for op in _yaml_ops(os.path.join(REF, fname)):
            out.setdefault(op, fname)
    return out


def collect_implemented():
    sys.path.insert(0, REPO)
    import paddle_tpu as paddle
    from paddle_tpu.ops.registry import all_ops

    names = set(all_ops().keys())
    # public API surfaces that count as the op being available
    import paddle_tpu.nn.functional as F
    from paddle_tpu.core.tensor import Tensor

    import paddle_tpu.geometric as geo
    import paddle_tpu.vision.ops as vops

    for mod in (paddle, F, paddle.linalg, paddle.fft, paddle.signal,
                paddle.text, geo, vops):
        names.update(n for n in dir(mod) if not n.startswith("_"))
    names.update(n for n in dir(Tensor) if not n.startswith("_"))
    return names


# note-token resolution roots (covered-by claims are VERIFIED against
# these — a stale symbol fails the audit; VERDICT r4 Weak #5)
def _resolution_roots():
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor

    return {
        "paddle": paddle,
        "F": paddle.nn.functional,
        "Tensor": Tensor,
        "nn": paddle.nn,
        "optimizer": paddle.optimizer,
        "distributed": paddle.distributed,
        "incubate": paddle.incubate,
        "amp": paddle.amp,
        "quantization": paddle.quantization,
        "distribution": paddle.distribution,
        "metric": paddle.metric,
        "inference": paddle.inference,
        "jit": paddle.jit,
    }


_TOKEN_RE = re.compile(
    r"\b(paddle|F|Tensor|nn|optimizer|distributed|incubate|amp|"
    r"quantization|distribution|metric|inference|jit)"
    r"((?:\.[A-Za-z_][A-Za-z0-9_]*)+)")
_PATH_RE = re.compile(r"\b([\w/]+\.(?:py|cc))\b")


def verify_note(note, roots):
    """Resolve every dotted-symbol and file-path token in a covered-by
    note. Returns a list of unresolvable tokens (empty = claim holds).
    Notes without tokens are prose and pass vacuously."""
    bad = []
    for m in _TOKEN_RE.finditer(note):
        obj = roots[m.group(1)]
        for attr in m.group(2)[1:].split("."):
            if attr.endswith("_") and not hasattr(obj, attr) \
                    and hasattr(obj, attr[:-1]):
                attr = attr[:-1]  # trailing _ from inplace spellings
            if not hasattr(obj, attr):
                bad.append(m.group(0))
                break
            obj = getattr(obj, attr)
    for m in _PATH_RE.finditer(note):
        rel = m.group(1)
        if not (os.path.exists(os.path.join(REPO, rel)) or
                os.path.exists(os.path.join(REPO, "paddle_tpu", rel))):
            bad.append(rel)
    return bad


def classify(ref_ops, impl):
    rows = []
    for op, src in sorted(ref_ops.items()):
        base = re.sub(r"_$", "", op)
        if op in impl or base in impl:
            rows.append((op, src, "implemented", ""))
            continue
        # inplace variants (op_) and _grad pairs
        if op.endswith("_grad") and (op[:-5] in impl
                                     or op[:-5] in ref_ops):
            rows.append((op, src, "implemented",
                         "gradient comes from jax.vjp of the forward"))
            continue
        if op in COVERED_BY:
            rows.append((op, src, "covered-by", COVERED_BY[op]))
            continue
        for pat, why in BY_DESIGN_PATTERNS:
            if re.search(pat, op):
                rows.append((op, src, "by-design", why))
                break
        else:
            rows.append((op, src, "missing", ""))
    return rows


def main():
    ref_ops = collect_reference_ops()
    impl = collect_implemented()
    rows = classify(ref_ops, impl)
    # verify covered-by claims: any unresolvable symbol/path demotes the
    # row to missing, so a stale claim can never hide behind "0 missing"
    roots = _resolution_roots()
    checked = []
    for op, src, cat, note in rows:
        if cat == "covered-by":
            bad = verify_note(note, roots)
            if bad:
                cat, note = "missing", \
                    f"STALE covered-by claim (unresolved: {bad})"
        checked.append((op, src, cat, note))
    rows = checked
    counts = {}
    for _, _, cat, _ in rows:
        counts[cat] = counts.get(cat, 0) + 1
    lines = [
        "# Op-surface audit (generated by tools/op_audit.py)",
        "",
        "Reference registry: paddle/phi/api/yaml/{ops,legacy_ops,"
        "fused_ops}.yaml — the single source of op truth (SURVEY §1).",
        f"Total reference ops: {len(rows)}. "
        + ", ".join(f"{k}: {v}" for k, v in sorted(counts.items())),
        "",
        "| op | yaml | status | note |",
        "|---|---|---|---|",
    ]
    for op, src, cat, note in rows:
        lines.append(f"| {op} | {src} | {cat} | {note} |")
    with open(os.path.join(REPO, "OPS_AUDIT.md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote OPS_AUDIT.md: {len(rows)} ops, {counts}")
    missing = [op for op, _, cat, _ in rows if cat == "missing"]
    print("missing:", missing)


if __name__ == "__main__":
    main()
