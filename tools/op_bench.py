"""Op-level benchmark harness + eager-dispatch microbenchmark.

TPU-native equivalent of the reference's op benchmark CI gate
(reference: tools/ci_op_benchmark.sh:1 runs benchmark/api tests per PR;
tools/check_op_benchmark_result.py compares logs and flags
regressions). Here:

  python tools/op_bench.py                  # writes OPBENCH_r{N}.json
  python tools/op_bench.py --compare A B    # gate: >10% regressions

Measures, for ~30 representative ops: EAGER latency (the full
dispatch + device round-trip a user pays per op outside jit — the cost
the reference's PHI eager dispatch exists to minimize, phi/README.md
§1.2) and JIT latency (the op inside a cached compiled program). Also
reports the raw Python dispatch overhead (eager_apply bookkeeping on
top of a bare jax call) and tape overhead (requires-grad dispatch).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPS = 30
WARMUP = 5


def _block(out):
    """Block on device completion for jax arrays AND paddle Tensors —
    jax.block_until_ready silently no-ops on non-pytree Tensor objects,
    which would time async dispatch enqueue instead of execution."""
    import jax

    if isinstance(out, (list, tuple)):
        for o in out:
            _block(o)
        return
    data = getattr(out, "_data", out)
    jax.block_until_ready(data)


def _median_us(fn, reps=REPS, warmup=WARMUP):
    for _ in range(warmup):
        out = fn()
    _block(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        _block(out)
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts) * 1e6)


def _suite():
    """(name, fn, tensor_args) for ~30 representative ops over realistic
    shapes. fn takes Tensors as POSITIONAL args so the jit measurement
    can pass them as program arguments — zero-arg jitted programs
    (inputs baked as constants) permanently degrade dispatch on the
    tunneled TPU platform."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    rng = np.random.RandomState(0)

    def t(*shape, dtype="float32"):
        return paddle.to_tensor(rng.randn(*shape).astype(dtype))

    a = t(256, 256)
    b = t(256, 256)
    big = t(1024, 1024)
    big2 = t(1024, 1024)
    v = t(65536)
    img = t(8, 16, 32, 32)
    logits = t(128, 1000)
    labels = paddle.to_tensor(rng.randint(0, 1000, (128,)))
    idx = paddle.to_tensor(rng.randint(0, 256, (64,)))
    q = t(4, 128, 8, 64)

    conv_w = t(32, 16, 3, 3)
    ln_w, ln_b = t(1024), t(1024)

    ops = [
        ("add", lambda a, b: a + b, (a, b)),
        ("multiply", lambda a, b: a * b, (a, b)),
        ("matmul_256", lambda a, b: a @ b, (a, b)),
        ("matmul_1024", lambda x, y: x @ y, (big, big2)),
        ("sum", lambda v: v.sum(), (v,)),
        ("mean_axis", lambda x: x.mean(axis=1), (big,)),
        ("max_reduce", lambda x: x.max(), (big,)),
        ("exp", lambda v: v.exp(), (v,)),
        ("sqrt", lambda v: v.abs().sqrt(), (v,)),
        ("relu", lambda x: F.relu(x), (big,)),
        ("gelu", lambda x: F.gelu(x), (big,)),
        ("sigmoid", lambda x: F.sigmoid(x), (big,)),
        ("softmax", lambda l: F.softmax(l, axis=-1), (logits,)),
        ("log_softmax", lambda l: F.log_softmax(l, axis=-1), (logits,)),
        ("cross_entropy", lambda l, y: F.cross_entropy(l, y),
         (logits, labels)),
        ("layer_norm", lambda x, w, b: F.layer_norm(x, [1024], w, b),
         (big, ln_w, ln_b)),
        ("reshape", lambda x: x.reshape([256, 4096]), (big,)),
        ("transpose", lambda x: x.transpose([1, 0]), (big,)),
        ("concat", lambda a, b: paddle.concat([a, b], axis=0), (a, b)),
        ("split", lambda x: paddle.split(x, 4, axis=0), (big,)),
        ("slice", lambda x: x[128:512, 128:512], (big,)),
        ("gather", lambda a, i: paddle.gather(a, i), (a, idx)),
        ("index_select", lambda a, i: paddle.index_select(a, i),
         (a, idx)),
        ("where", lambda a, b: paddle.where(a > 0, a, b), (a, b)),
        ("cast", lambda x: x.astype("bfloat16"), (big,)),
        ("clip", lambda x: x.clip(-1.0, 1.0), (big,)),
        ("cumsum", lambda v: v.cumsum(), (v,)),
        ("argmax", lambda l: l.argmax(axis=-1), (logits,)),
        ("sort", lambda v: paddle.sort(v), (v,)),
        ("conv2d", lambda x, w: F.conv2d(x, w, padding=1),
         (img, conv_w)),
        ("max_pool2d", lambda x: F.max_pool2d(x, 2), (img,)),
        ("sdp_attention", lambda q: F.scaled_dot_product_attention(
            q, q, q, is_causal=True), (q,)),
    ]
    return ops


def _taped_backward_us(fn, targs, reps=10, warmup=3):
    """Median forward+backward latency through the taped (requires-grad)
    dispatch — the path the aval-keyed VJP cache amortizes. None for ops
    without a differentiable float input (or whose output can't reduce
    to a scalar loss)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle

    leafs = []
    any_diff = False
    for t in targs:
        diff = jnp.issubdtype(t._data.dtype, jnp.inexact)
        any_diff = any_diff or diff
        leafs.append(paddle.to_tensor(np.asarray(t._data),
                                      stop_gradient=not diff))
    if not any_diff:
        return None

    def run():
        out = fn(*leafs)
        if isinstance(out, (list, tuple)):
            out = out[0]
        out.sum().backward()
        for leaf in leafs:
            leaf.clear_grad()
        return out

    try:
        return _median_us(run, reps=reps, warmup=warmup)
    except Exception:
        return None


def run_bench():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor

    results = {}
    for name, fn, targs in _suite():
        arrays = [t._data for t in targs]
        eager_us = _median_us(lambda: fn(*targs))

        def jit_wrap(*arrs, f=fn):
            out = f(*[Tensor(x) for x in arrs])
            return out[0]._data if isinstance(out, (list, tuple)) \
                else out._data

        jit_fn = jax.jit(jit_wrap)
        jit_us = _median_us(lambda: jit_fn(*arrays))
        taped_us = _taped_backward_us(fn, targs)
        results[name] = {"eager_us": round(eager_us, 1),
                         "jit_us": round(jit_us, 1),
                         "taped_backward_us": (None if taped_us is None
                                               else round(taped_us, 1))}

    # ---- dispatch overhead decomposition (phi/README.md §1.2) ----
    # baseline = a pre-compiled jax program call: the true floor for one
    # device op. (Bare eager jnp.add is NOT the floor on the axon TPU
    # platform — per-op eager mode there takes a pathological ~100ms
    # path, which is exactly why this framework's eager dispatch wraps
    # ops in cached jit computations, FLAGS_eager_jit_ops.)
    x = jnp.ones((8,), jnp.float32)
    jadd = jax.jit(jnp.add)
    jadd(x, x)
    base_us = _median_us(lambda: jadd(x, x), reps=200)
    t0 = paddle.to_tensor(np.ones((8,), np.float32))
    nograd_us = _median_us(lambda: t0 + t0, reps=200)
    tg = paddle.to_tensor(np.ones((8,), np.float32), stop_gradient=False)

    def taped():
        with_grad = tg + tg
        return with_grad

    tape_us = _median_us(taped, reps=200)
    overhead = {
        "bare_jax_us": round(base_us, 1),
        "eager_dispatch_us": round(nograd_us, 1),
        "eager_dispatch_overhead_us": round(nograd_us - base_us, 1),
        "taped_dispatch_us": round(tape_us, 1),
        "tape_overhead_us": round(tape_us - nograd_us, 1),
    }
    # runtime telemetry for the whole bench run (profiler.stats): VJP
    # trace-cache outcomes + compile-time histograms — the hit rate here
    # is what the taped_dispatch_us number is made of
    from paddle_tpu.profiler import stats

    snap = stats.snapshot()
    telemetry = {
        "counters": {k: v for k, v in snap["counters"].items()
                     if not k.startswith("op.")},
        "histograms": snap["histograms"],
        "total_op_dispatches": sum(
            v for k, v in snap["counters"].items()
            if k.startswith("op.")),
    }
    hr = stats.vjp_cache_hit_rate()
    if hr is not None:
        telemetry["vjp_cache_hit_rate"] = round(hr, 4)
    fhr = stats.fwd_cache_hit_rate()
    if fhr is not None:
        telemetry["fwd_cache_hit_rate"] = round(fhr, 4)
    return {
        "backend": jax.default_backend(),
        "device": getattr(jax.devices()[0], "device_kind", "cpu"),
        "reps": REPS,
        "dispatch": overhead,
        "ops": results,
        "telemetry": telemetry,
    }


def compare(prev_path: str, cur_path: str, tol: float = 0.10) -> int:
    """Exit non-zero when any op's eager or jit latency regressed by
    more than ``tol`` vs the previous round (the
    check_op_benchmark_result.py gate)."""
    with open(prev_path) as f:
        prev = json.load(f)
    with open(cur_path) as f:
        cur = json.load(f)
    if prev.get("backend") != cur.get("backend"):
        print(f"op_bench: backend changed "
              f"({prev.get('backend')} -> {cur.get('backend')}); "
              "comparison skipped")
        return 0
    bad = []
    # dispatch overheads are gated too (taped dispatch in particular:
    # the r5 vjp-trace cache took it 753us -> ~50us; a revert must fail)
    for k in ("eager_dispatch_us", "taped_dispatch_us"):
        p, c = prev["dispatch"].get(k), cur["dispatch"].get(k)
        if p and c and c > max(p * (1 + tol), p + 10.0):
            bad.append(f"dispatch.{k}: {p} -> {c} us "
                       f"(+{100 * (c / p - 1):.0f}%)")
    for name, c in cur["ops"].items():
        p = prev["ops"].get(name)
        if not p:
            continue
        for k in ("eager_us", "jit_us", "taped_backward_us"):
            pv, cv = p.get(k), c.get(k)
            if pv is None or cv is None:  # column absent in older rounds
                continue
            # guard tiny-latency noise with a 5us floor
            if cv > max(pv * (1 + tol), pv + 5.0):
                bad.append(f"{name}.{k}: {pv} -> {cv} us "
                           f"(+{100 * (cv / pv - 1):.0f}%)")
    if bad:
        print("op_bench REGRESSIONS (>10%):")
        for line in bad:
            print(" ", line)
        return 1
    print(f"op_bench: no regressions vs {os.path.basename(prev_path)} "
          f"({len(cur['ops'])} ops)")
    return 0


def _next_round_path(repo: str) -> str:
    rounds = [int(m.group(1)) for f in glob.glob(
        os.path.join(repo, "OPBENCH_r*.json"))
        if (m := re.search(r"OPBENCH_r(\d+)\.json$", f))]
    return os.path.join(repo, f"OPBENCH_r{max(rounds, default=0) + 1:02d}.json")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--compare", nargs=2, metavar=("PREV", "CUR"))
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if args.compare:
        sys.exit(compare(*args.compare))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = args.out or _next_round_path(repo)
    res = run_bench()
    with open(out, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps({"wrote": out,
                      "dispatch": res["dispatch"],
                      "n_ops": len(res["ops"])}))
    # auto-gate vs the previous round's file when present
    prevs = sorted(p for p in glob.glob(
        os.path.join(repo, "OPBENCH_r*.json")) if p != out)
    if prevs:
        sys.exit(compare(prevs[-1], out))


if __name__ == "__main__":
    main()
