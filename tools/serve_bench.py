"""Poisson-load serving benchmark: SLO numbers for the serving frontend.

Drives ``paddle_tpu.serving.ServingEngine`` the way traffic does — a
seeded Poisson arrival process submits N concurrent streams of mixed
prompt lengths from a background thread while the scheduler loop runs
— and prints ONE JSON line with the SLO rungs ``tools/bench_gate.py``
gates (TTFT regresses UP, throughput DOWN):

    python tools/serve_bench.py --streams 8 --seed 0

    {"serve_p50_ttft_ms": ..., "serve_p99_ttft_ms": ...,
     "serve_tokens_per_sec": ..., "serve_goodput": ...,
     ..., "telemetry": {...}}

``serve_goodput`` is the fraction of finished requests meeting BOTH
the ``--ttft-target`` and ``--tpot-target`` SLOs (verdicts stamped
per request by serving/slo.py). ``--requests-out`` writes one JSONL
row per request (waits/ttft/tpot/preempt counts/verdict) and
``--journal-out`` dumps the flight recorder for
``tools/serve_top.py`` forensics.

Defaults are CPU-sized (tiny model) so the rung runs in CI; on a chip
pass the 1.3B geometry (--d-model 2048 --layers 24 --heads 16
--vocab 51200) and a rate that saturates it. A warmup pass compiles
every chunk/decode program first (--no-warmup to include compiles in
the measured TTFTs — the cold-start view).

``--speculative`` (ISSUE 12) runs the scheduler's decode slot as
draft+verify rounds (``--spec-drafter self|draft|oracle``,
``--spec-k``): every ``serve_*`` key re-emits as ``serve_spec_*`` plus
``serve_spec_accept_rate`` / ``serve_spec_rounds``, so bench_gate
tracks the speculative SLO rungs (throughput/accept-rate regress
DOWN, TTFT UP) independently of the plain ones. ``oracle`` drives the
target model as its own drafter — the acceptance-ceiling workload.

``--fleet N`` (ISSUE 14) drives a :class:`FleetRouter` over N
replicas (one serve-loop thread each) under a SKEWED-PREFIX Poisson
load — ``--system-prompts K`` distinct system prompts with Zipf-ish
popularity — and emits ``fleet_{goodput,tokens_per_sec,p50_ttft_ms,
p99_ttft_ms,failovers,migrations,...}``. ``--fleet-policy rr`` runs
the round-robin baseline the affinity policy is pinned against.
``--fleet --chaos`` re-drives the measured workload with a seeded
fleet fault schedule (a replica KILL mid-load, a hang, dispatch
faults, beat suppression) and pins the ISSUE 14 acceptance: zero
admitted requests lost, survivor greedy-token parity vs the
undisturbed run, and bounded goodput loss (``fleet_chaos_*`` keys,
nonzero exit on a failed pin).

``--fleet --drain-async`` (ISSUE 19) gracefully drains replica 0
MID-LOAD with ``FLAGS_migrate_async`` on: each occupied decode slot
streams its complete KV pages to a peer in page batches while both
endpoints keep decoding, and only the mutable tail + metadata copy
under the step locks at the join. Emits ``fleet_async_migration_*``
(streamed migration count, total migration stall-ms, decode tokens
generated fleet-wide during the drain window) and exits nonzero when
nothing streamed, decode made no progress during the drain, or any
request was lost.

``--chaos`` (ISSUE 11) re-drives the SAME measured workload against a
fresh engine with a seeded fault schedule installed
(``serving/faults.py`` — raises, delays, token corruption, and pool
squeezes across >=5 distinct sites) and pins the robustness
acceptance: the serve loop never exits, every faulted request lands
in a terminal ``error``/``deadline_exceeded``/``shed`` state, every
SURVIVING request's greedy tokens are identical to the fault-free
run, and goodput stays within a pinned bound of the fault-free run's.
Emits ``serve_chaos_*`` keys (gated by tools/bench_gate.py) and exits
nonzero when any pin fails.

``--adapters K`` (ISSUE 18) serves K distinct LoRA adapters from one
:class:`AdapterBank` — every request is stamped with a round-robin
``adapter_id`` so each decode chunk mixes adapters and the batched
ragged grouped-GEMM delta path carries the whole set in ONE launch
per target projection. The rung measures the multi-tenancy tax
directly: the same workload is first driven single-tenant (every
request on ONE adapter — the same adaptered programs, no grouping
spread) and then multi-adapter, and ``serve_lora_pct_of_single_
tenant`` is the ratio of the two throughputs (gated DOWN; the ISSUE
18 acceptance pins >= 0.8 at K=32 on CPU). Also emits
``serve_lora_{tokens_per_sec,swap_count,decode_programs}`` — the
program count must stay independent of the adapter set.

``--tenants K`` (ISSUE 17) stamps a Zipf-popular tenant id on every
request (rank k drawn ∝ 1/(k+1)^``--tenant-skew``) and turns the
per-tenant usage ledger on (``serving/accounting.py``): the run emits
``serve_tenant_{count,max_share,min_goodput}`` and
``usage_unattributed_ms`` — the last gated UP by bench_gate with NO
noise floor (device time the ledger failed to attribute is an
accounting leak however small). ``--usage-out`` dumps the per-request
usage JSONL (``serve_top --tenants`` / ``trace_merge`` input; fleet
runs write one ``_r<idx>`` file per replica plus ``_router``). The
usage keys are ALWAYS emitted with ledger-off defaults so the gated
key set is stable across runs.
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def _telemetry():
    """Runtime-telemetry block (the bench.py shape): stats registry
    snapshot + the per-program roofline table, so the serve rungs
    carry the serve.{ttft,tpot,queue_wait} histograms and the
    per-phase ``serve.prefill[c=*]`` / ``decode.*[k=*]`` rows."""
    from paddle_tpu.profiler import roofline, stats

    snap = stats.snapshot()
    out = {
        "counters": {k: v for k, v in snap["counters"].items()
                     if not k.startswith("op.")},
        "gauges": snap["gauges"],
        "histograms": snap["histograms"],
    }
    rl = roofline.report()
    if rl:
        out["roofline"] = {k: v for k, v in rl.items()
                           if k.startswith(("serve", "decode",
                                            "prefill"))}
    return out


def _start_telemetry(args, journal=None, n_replicas=None):
    """Continuous-telemetry wiring (ISSUE 16): when --telemetry-out
    is set, run a background TimeSeriesSampler over the stats
    registry for the measured window with the default alert rules
    attached (burn-rate, HBM pressure, replica-death when fleet,
    preemption spike), journaling alert transitions into the serve's
    flight recorder. Returns the sampler or None."""
    if not getattr(args, "telemetry_out", None):
        return None
    from paddle_tpu.profiler import AlertEngine, TimeSeriesSampler
    from paddle_tpu.profiler import default_rules

    alerts = AlertEngine(default_rules(n_replicas), journal=journal)
    sampler = TimeSeriesSampler(
        interval_ms=args.telemetry_interval_ms,
        enabled=True).attach_alerts(alerts)
    sampler.start()
    return sampler


def _stop_telemetry(sampler, path):
    """Stop the measured window's sampler (one final tick) and dump
    the series JSONL (serve_top --history / trace_merge input)."""
    if sampler is None:
        return {}
    sampler.stop()
    sampler.dump_jsonl(path)
    return {"telemetry_ticks": sampler.n_ticks,
            "telemetry_out": path}


def _alert_keys():
    """The gated alert/attribution scalars — emitted on every run
    (zero when telemetry is off) so bench_gate can hold the line:
    ``alert_fired`` UP with no noise floor (a run that starts paging
    is a regression however small), host overhead UP (the residual
    the attribution exists to expose)."""
    from paddle_tpu.profiler import stats

    h = stats.histogram("serve.step.host_overhead_ms")
    return {
        "alert_fired": int(stats.counter("alert.fired").value),
        "alert_resolved": int(stats.counter("alert.resolved").value),
        "serve_step_host_overhead_ms": round(h.total / h.count, 4)
        if h.count else None,
    }


def _usage_keys(eng=None, router=None):
    """The per-tenant usage scalars (ISSUE 17) — ALWAYS emitted, with
    ledger-off defaults, so bench_gate's gated key set is stable:
    ``serve_tenant_max_share`` regresses UP (one tenant crowding out
    the rest) and ``usage_unattributed_ms`` UP with no noise floor."""
    from paddle_tpu.serving.accounting import (tenant_rollup,
                                               unattributed_ms)

    if router is not None:
        ledgers = [r.eng.usage for r in router.replicas
                   if r.eng.usage is not None]
        if router.usage is not None:
            ledgers.append(router.usage)
        recs = router.fleet_usage() if ledgers else []
        mons = [r.eng.slo_monitor for r in router.replicas]
    else:
        ledgers = [eng.usage] if eng.usage is not None else []
        recs = eng.usage.records() if ledgers else []
        mons = [eng.slo_monitor]
    if not ledgers:
        return {"serve_tenant_count": 0,
                "serve_tenant_max_share": 0.0,
                "serve_tenant_min_goodput": None,
                "usage_unattributed_ms": 0.0}
    roll = tenant_rollup(recs)
    goodputs = [m.tenant_min_goodput for m in mons
                if m.tenant_min_goodput is not None]
    return {
        "serve_tenant_count": len(roll),
        "serve_tenant_max_share": round(max(
            (t["share"] for t in roll.values()), default=0.0), 4),
        "serve_tenant_min_goodput": round(min(goodputs), 4)
        if goodputs else None,
        "usage_unattributed_ms": unattributed_ms(*ledgers),
    }


def _dump_usage(args, eng=None, router=None):
    """--usage-out: per-request usage JSONL. Single engine writes one
    hop-0 file; a fleet writes the export_journals shape — one
    ``<prefix>_r<idx>.jsonl`` per replica plus ``<prefix>_router`` —
    which trace_merge folds back into one record per request."""
    if not args.usage_out:
        return
    if router is not None:
        import os

        d = os.path.dirname(args.usage_out) or "."
        base = os.path.basename(args.usage_out)
        router.export_usage(d, prefix=base.replace(".jsonl", ""))
    elif eng is not None and eng.usage is not None:
        eng.usage.dump_jsonl(args.usage_out, hop=0)


def build_engine(args, faults=None):
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference import FusedCausalLM
    from paddle_tpu.serving import ServingEngine, SLOConfig

    paddle.seed(args.seed)
    lens = [int(x) for x in args.prompt_mix.split(",")]
    max_len = max(lens) + args.system_prompt + args.max_new + 1
    model = FusedCausalLM(
        vocab_size=args.vocab, embed_dim=args.d_model,
        num_heads=args.heads, dim_feedforward=4 * args.d_model,
        num_layers=args.layers, max_position=max_len + 1)
    if args.bf16:
        st = model.stack
        for n in ("qkv_weight", "qkv_bias", "out_weight", "out_bias",
                  "ffn1_weight", "ffn1_bias", "ffn2_weight",
                  "ffn2_bias"):
            p = getattr(st, n)
            p._rebind(p._data.astype(jnp.bfloat16))
    slo = SLOConfig(ttft_weight=args.ttft_weight,
                    tpot_weight=args.tpot_weight,
                    prefill_chunk=args.prefill_chunk,
                    ttft_target_ms=args.ttft_target,
                    tpot_target_ms=args.tpot_target)
    spec = None
    if getattr(args, "speculative", False):
        spec = _build_drafter(args, model, max_len)
    return ServingEngine(
        model, max_batch=args.streams, page_size=args.page_size,
        max_length=max_len, decode_chunk=args.decode_chunk,
        quant=args.quant, slo=slo, faults=faults,
        speculative=spec, spec_k=args.spec_k,
        mp_degree=args.mp if args.mp and args.mp > 1 else None), lens


def _build_drafter(args, model, max_len):
    """--spec-drafter resolution: ``self`` = Medusa-style training-free
    heads (no extra weights); ``draft`` = a quarter-size FusedCausalLM
    draft model with its own tiny non-paged KV state; ``oracle`` =
    the target model ITSELF as draft model — every draft is the
    target's own greedy pick, accept rate 1.0, the amortization
    ceiling rung (an acceptance-friendly workload by construction)."""
    from paddle_tpu.inference import DraftModelDrafter, FusedCausalLM

    if args.spec_drafter == "self":
        return "self"
    if args.spec_drafter == "oracle":
        return DraftModelDrafter(model)
    import paddle_tpu as paddle

    paddle.seed(args.seed + 1)
    draft = FusedCausalLM(
        vocab_size=args.vocab, embed_dim=max(args.d_model // 4, 8),
        num_heads=max(args.heads // 2, 1),
        dim_feedforward=max(args.d_model, 32),
        num_layers=max(args.layers // 2, 1),
        max_position=max_len + 1)
    return DraftModelDrafter(draft)


def make_requests(args, lens, rng):
    """(prompt, arrival_gap_s) list: mixed lengths, a shared system
    prompt on a fraction of requests (the prefix-cache's traffic
    shape), exponential inter-arrival gaps (Poisson process)."""
    sys_prompt = rng.randint(0, args.vocab, (args.system_prompt,)) \
        if args.system_prompt else None
    reqs = []
    for i in range(args.requests):
        L = int(lens[int(rng.randint(len(lens)))])
        body = rng.randint(0, args.vocab, (L,))
        if sys_prompt is not None and rng.rand() < args.system_frac:
            prompt = np.concatenate([sys_prompt, body])
        else:
            prompt = body
        gap = float(rng.exponential(1.0 / args.rate))
        reqs.append((prompt, gap))
    return reqs


def _assign_tenants(reqs, args, rng):
    """Stamp a Zipf-popular tenant id on every request — rank k drawn
    ∝ 1/(k+1)^``--tenant-skew`` — turning ``(prompt, gap)`` pairs into
    ``(prompt, gap, tenant)`` triples. The skew is what makes
    ``tenant.max_share`` move: a uniform tenant mix never trips the
    tenant-hog alert rule."""
    k = max(int(args.tenants), 1)
    w = np.array([1.0 / (i + 1) ** args.tenant_skew
                  for i in range(k)])
    w /= w.sum()
    return [(p, g, f"tenant{int(rng.choice(k, p=w))}")
            for p, g in reqs]


def drive(eng, reqs, max_new, deadline_ms=None):
    """Submit on a background thread at the Poisson arrival times;
    run the scheduler loop here until every submitted request reaches
    a TERMINAL state (ok, error, deadline_exceeded, shed-at-drain).
    Returns ``(wall_s, rids)`` — ``rids[i]`` is submission i's request
    id, or None when the engine shed it at submit (typed
    ServerOverloaded backpressure)."""
    from paddle_tpu.serving import ServerOverloaded

    err: list = []
    rids: list = []
    done_submitting = threading.Event()

    def submitter():
        try:
            t_next = time.monotonic()
            for prompt, gap, *rest in reqs:
                t_next += gap
                delay = t_next - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                try:
                    rids.append(eng.submit(prompt,
                                           max_new_tokens=max_new,
                                           deadline_ms=deadline_ms,
                                           tenant=rest[0] if rest
                                           else None,
                                           adapter_id=rest[1]
                                           if len(rest) > 1 else None))
                except ServerOverloaded:
                    rids.append(None)  # backpressure — dropped load
        except BaseException as e:  # surface on the main thread
            err.append(e)
        finally:
            done_submitting.set()

    th = threading.Thread(target=submitter, daemon=True)
    t0 = time.monotonic()
    th.start()
    while True:
        if err:
            raise err[0]
        if done_submitting.is_set() and len(eng.finished) >= sum(
                1 for r in rids if r is not None):
            break
        if (eng._inbox or eng.waiting or eng._prefilling
                or eng.num_active):
            eng.step()
        else:
            time.sleep(0.0005)  # idle: wait for the next arrival
    th.join()
    return time.monotonic() - t0, list(rids)


def make_fleet_requests(args, lens, rng):
    """Skewed-prefix Poisson load (the fleet routing workload):
    ``--system-prompts`` DISTINCT system prompts with Zipf-ish
    popularity (rank k drawn ∝ 1/(k+1)), mixed body lengths,
    exponential inter-arrival gaps. Returns (prompt, gap) pairs."""
    k = max(int(args.system_prompts), 1)
    prefixes = [rng.randint(0, args.vocab, (args.system_prompt,))
                for _ in range(k)]
    w = np.array([1.0 / (i + 1) for i in range(k)])
    w /= w.sum()
    reqs = []
    for _ in range(args.requests):
        L = int(lens[int(rng.randint(len(lens)))])
        body = rng.randint(0, args.vocab, (L,))
        if args.system_prompt and rng.rand() < args.system_frac:
            prompt = np.concatenate(
                [prefixes[int(rng.choice(k, p=w))], body])
        else:
            prompt = body
        reqs.append((prompt, float(rng.exponential(1.0 / args.rate))))
    return reqs, prefixes


def build_fleet(args, faults=None, disagg=None):
    """N identical replicas from one seeded factory (failover replays
    and page migration are byte-exact only because every replica
    computes the same function). ``disagg`` forwards the ISSUE 20
    prefill/decode role split ('auto' or 'P:D'); decode-role replicas
    run role-specialized config — same seeded weights (KV handoffs
    stay byte-exact) but DOUBLE the decode batch (their work is
    admission-free token streaming, so the extra slots cost only
    page-pool headroom and keep prefill handoffs from bouncing off a
    full batch back onto the prefill side) and a QUARTER decode
    chunk (frequent step boundaries, so an inbound handoff's
    import never waits behind a long decode action's step lock)."""
    from paddle_tpu.serving import FleetRouter
    from paddle_tpu.serving.router import _parse_disagg

    roles = _parse_disagg(disagg, args.fleet)

    def factory(i):
        streams0, dchunk0 = args.streams, args.decode_chunk
        if roles is not None and i >= roles[0]:
            args.streams = streams0 * 2
            args.decode_chunk = max(2, dchunk0 // 4)
        try:
            eng, _ = build_engine(args)
        finally:
            args.streams, args.decode_chunk = streams0, dchunk0
        return eng

    lens = [int(x) for x in args.prompt_mix.split(",")]
    return FleetRouter(engine_factory=factory, n_replicas=args.fleet,
                       policy=args.fleet_policy, faults=faults,
                       disagg=disagg), lens


def _fleet_warm(router, args, lens, prefixes):
    """Compile every chunk/decode program on every replica OUTSIDE
    the measured window (synchronous stepping — no beat enforcement,
    so multi-second compiles can't false-kill a replica), then reset
    telemetry/journals to describe only the load run."""
    from paddle_tpu.profiler import stats
    from paddle_tpu.serving import Request

    warm = [np.full((L,), 1, np.int32) for L in lens]
    if args.system_prompt:
        warm += [np.concatenate([p, warm[0]]) for p in prefixes]
    for rep in router.replicas:      # every replica compiles
        for p in warm:
            rep.eng.submit_request(
                Request(p, max_new_tokens=args.max_new))
    while any(r.eng.has_work for r in router.replicas):
        for rep in router.replicas:
            rep.step_once()
    if router.disagg is not None or any(
            getattr(r.eng, "host_tier", None) is not None
            for r in router.replicas):
        _warm_kv_transfer(router)
    for rep in router.replicas:
        rep.eng.finished.clear()
        rep.eng.action_log.clear()
        rep.eng.slo_monitor.reset()
        if rep.eng.journal is not None:
            rep.eng.journal.clear()
        if rep.eng.usage is not None:
            rep.eng.usage.reset()   # the ledger describes the load run
    if router.usage is not None:
        router.usage.reset()
    router._tracked.clear()
    stats.reset()


def _warm_kv_transfer(router):
    """Compile the page-count-BUCKETED KV gather/scatter programs
    (handoff export/import, host-tier spill/restore — see
    ``ContinuousBatchingEngine._pad_pow2``) outside the measured
    window: export doubling page batches and write the blobs straight
    back to the same pages (byte-identical, so pool contents are
    untouched). Without this the FIRST mid-drive handoff or spill
    pays a multi-hundred-ms XLA compile inside a replica's stepping
    thread and the health checker hedges its queue away."""
    for rep in router.replicas:
        eng = rep.eng
        if not eng.can_spill():
            continue
        cap = max(1, min(eng._mgr.num_pages,
                         getattr(eng, "_pages_per_seq", 1 << 30)))
        n = 1
        while True:
            pages = list(range(min(n, cap)))
            eng.import_kv_pages(pages, eng.export_kv_pages(pages))
            if n >= cap:
                break
            n *= 2


def drive_fleet(router, reqs, max_new, deadline_ms=None,
                timeout_s=600.0):
    """Threaded fleet drive: start the replica loops + health monitor,
    submit at the Poisson arrival times, wait until every tracked
    request is terminal. Returns (wall_s, rids) with None for
    router-shed submissions."""
    from paddle_tpu.serving import ServerOverloaded

    router.start()
    rids = []
    t0 = time.monotonic()
    t_next = t0
    for prompt, gap, *rest in reqs:
        t_next += gap
        delay = t_next - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            rids.append(router.submit(prompt, max_new_tokens=max_new,
                                      deadline_ms=deadline_ms,
                                      tenant=rest[0] if rest
                                      else None))
        except ServerOverloaded:
            rids.append(None)
    deadline = time.monotonic() + timeout_s
    while router.pending():
        if time.monotonic() > deadline:
            router.stop()
            raise RuntimeError(
                f"fleet bench stalled: {router.pending()} requests "
                f"in flight, replica states "
                f"{[r.state for r in router.replicas]}")
        time.sleep(0.001)
    wall = time.monotonic() - t0
    router.stop()
    return wall, rids


def fleet_chaos_injector(seed):
    """Seeded FLEET fault schedule (>=5 distinct sites): a replica
    KILL mid-load (the headline crash), a replica.step hang long
    enough to walk suspect -> dead, suppressed heartbeats, dispatch
    faults that trip a circuit breaker, and engine-level chunk faults
    — all of which the router must absorb with zero lost requests."""
    from paddle_tpu.serving import FaultInjector

    return (FaultInjector(seed=seed)
            .add("replica.step", kind="kill", at=10)
            # the hang lands between the suspect (3 beats = 150ms)
            # and dead (6 beats = 300ms) thresholds: the replica is
            # suspected (inbox hedges away) and then RECOVERS — only
            # the kill above may take a replica down, so 1 of 2 dying
            # is exactly the zero-loss acceptance scenario
            .add("replica.step", kind="hang", at=30, delay_ms=200.0)
            .add("replica.heartbeat", kind="raise", at=(5, 6))
            .add("router.dispatch", kind="raise", at=(3, 7))
            .add("prefill.dispatch", kind="raise", at=4)
            .add("decode.step", kind="raise", at=6))


def _start_drainer(router):
    """--drain-async (ISSUE 19): once replica 0 is mid-decode, drain
    it with ``FLAGS_migrate_async`` on — occupied slots STREAM their
    complete KV pages to peers while both endpoints keep decoding —
    and measure fleet-wide decode progress during the drain window
    (the migration-concurrent-decode pin). Returns (thread, state)."""
    from paddle_tpu.core.flags import set_flags

    set_flags({"migrate_async": True})
    state = {}

    def _drainer():
        rep = router.replicas[0]
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if any(rep.eng._slots[i] is not None
                   for i in range(rep.eng.max_batch)):
                break
            time.sleep(0.001)
        tok0 = sum(len(r.generated) for r in router._tracked)
        t0 = time.monotonic()
        router.drain(0)
        while rep.state not in ("drained", "dead") \
                and time.monotonic() < deadline:
            time.sleep(0.001)
        state["decode_tokens"] = sum(
            len(r.generated) for r in router._tracked) - tok0
        state["drain_ms"] = round((time.monotonic() - t0) * 1e3, 3)

    th = threading.Thread(target=_drainer, daemon=True)
    th.start()
    return th, state


def run_fleet(args):
    """The --fleet bench: warmup, measured Poisson run, fleet_* keys;
    with --chaos, a second run under the seeded fleet fault schedule
    pinning zero-loss failover + survivor parity + bounded goodput
    loss; with --drain-async, a mid-load decode-concurrent drain of
    replica 0 pinning streamed async migrations + decode progress
    during the drain window (fleet_async_migration_* keys). Returns
    (out dict, ok)."""
    from paddle_tpu.profiler import stats

    rng = np.random.RandomState(args.seed)
    router, lens = build_fleet(args)
    reqs, prefixes = make_fleet_requests(args, lens, rng)
    if args.tenants:
        reqs = _assign_tenants(reqs, args, rng)
    if not args.no_warmup:
        _fleet_warm(router, args, lens, prefixes)
    sampler = _start_telemetry(
        args, journal=router.replicas[0].eng.journal,
        n_replicas=args.fleet)
    drainer = _start_drainer(router) if args.drain_async else None
    wall, rids = drive_fleet(router, reqs, args.max_new,
                             deadline_ms=args.deadline_ms)
    if drainer is not None:
        drainer[0].join(timeout=10.0)
    tele_out = _stop_telemetry(sampler, args.telemetry_out)
    done = router.results()
    finished = [done[r] for r in rids if r is not None]
    ttfts = np.array([r.ttft_s for r in finished
                      if r.ttft_s is not None], np.float64) * 1e3
    if ttfts.size == 0:
        ttfts = np.array([0.0])
    judged = [r for r in finished
              if getattr(r, "slo_ok", None) is not None]
    goodput = round(sum(1 for r in judged if r.slo_ok)
                    / len(judged), 4) if judged else None
    total_tokens = sum(len(r.generated) for r in finished)
    if args.journal_out:
        import os

        d = os.path.dirname(args.journal_out) or "."
        base = os.path.basename(args.journal_out)
        router.export_journals(d, prefix=base.replace(".jsonl", ""))
    _dump_usage(args, router=router)
    out = {
        "fleet_replicas": args.fleet,
        "fleet_policy": args.fleet_policy,
        "fleet_p50_ttft_ms": round(float(np.percentile(ttfts, 50)), 3),
        "fleet_p99_ttft_ms": round(float(np.percentile(ttfts, 99)), 3),
        "fleet_tokens_per_sec": round(total_tokens / wall, 1)
        if wall > 0 else None,
        "fleet_goodput": goodput,
        "fleet_requests": len(finished),
        "fleet_shed": sum(1 for r in rids if r is None),
        "fleet_failovers": int(
            stats.counter("fleet.failovers").value),
        "fleet_migrations": int(
            stats.counter("fleet.migrations").value),
        "fleet_migrated_pages": int(
            stats.counter("fleet.migrated_pages").value),
        "fleet_hedges": int(stats.counter("fleet.hedges").value),
        "fleet_prefix_pages_saved": int(
            stats.counter("serving.prefix_pages_saved").value),
        "fleet_system_prompts": int(args.system_prompts),
        "fleet_rate": args.rate,
        "fleet_wall_s": round(wall, 3),
        "telemetry": _telemetry(),
    }
    out.update(_alert_keys())
    out.update(_usage_keys(router=router))
    out.update(tele_out)
    ok = True
    if drainer is not None:
        h = stats.histogram("serve.step.migration_ms")
        st = drainer[1]
        out.update({
            "fleet_drain_async": 1,
            "fleet_async_migrations": int(
                stats.counter("fleet.async_migrations").value),
            # stall accounting: total migration phase time (gated UP —
            # overlap exists to shrink what migration steals)
            "fleet_async_migration_stall_ms": round(h.total, 3)
            if h.count else 0.0,
            # tokens generated FLEET-WIDE during the drain window:
            # the migration-concurrent decode-progress pin (gated
            # DOWN — zero means the drain serialized decode)
            "fleet_async_migration_decode_tokens": st.get(
                "decode_tokens"),
            "fleet_async_migration_drain_ms": st.get("drain_ms"),
        })
        lost = sum(1 for r in rids if r is not None
                   and getattr(done.get(r), "state", None) != "ok")
        out["fleet_async_migration_lost"] = lost
        ok = (out["fleet_async_migrations"] >= 1
              and (st.get("decode_tokens") or 0) > 0 and lost == 0)
    if args.chaos:
        chaos_out, chaos_ok = run_fleet_chaos(args, reqs, rids, done,
                                              goodput, lens, prefixes)
        out.update(chaos_out)
        ok = ok and chaos_ok
    return out, ok


def run_fleet_chaos(args, reqs, base_rids, base_done, base_goodput,
                    lens, prefixes):
    """Re-drive the measured fleet workload with the seeded fleet
    fault schedule armed (after a fault-free warmup). Pins the ISSUE
    14 acceptance: a replica dies mid-load yet ZERO admitted requests
    are lost — every one finishes ``ok`` on a survivor with greedy
    tokens identical to the undisturbed run — and goodput stays
    within a pinned bound."""
    from paddle_tpu.profiler import stats

    seed = args.chaos_seed if args.chaos_seed is not None \
        else args.seed
    inj = fleet_chaos_injector(seed)
    router, _ = build_fleet(args)
    if not args.no_warmup:
        _fleet_warm(router, args, lens, prefixes)
    router.install_faults(inj)
    # the chaos window gets its own sampler/series: the replica-death
    # alert must fire at the injected kill, in a dump of its own
    sampler = _start_telemetry(
        args, journal=router.replicas[0].eng.journal,
        n_replicas=args.fleet)
    t0 = time.monotonic()
    wall, rids = drive_fleet(router, reqs, args.max_new,
                             deadline_ms=args.deadline_ms)
    tele_out = _stop_telemetry(
        sampler, args.telemetry_out + ".chaos"
        if args.telemetry_out else None)
    done = router.results()
    survivors = mismatches = lost = 0
    shed = 0
    for idx, rid in enumerate(rids):
        if rid is None:
            shed += 1
            continue
        req = done.get(rid)
        if req is None or getattr(req, "state", None) != "ok":
            lost += 1
            continue
        survivors += 1
        brid = base_rids[idx] if idx < len(base_rids) else None
        base = base_done.get(brid) if brid is not None else None
        if base is not None and \
                list(base.generated) != list(req.generated):
            mismatches += 1
    judged = [r for r in done.values()
              if getattr(r, "slo_ok", None) is not None]
    goodput = round(sum(1 for r in judged if r.slo_ok)
                    / len(judged), 4) if judged else None
    parity = 1.0 if mismatches == 0 and survivors > 0 else 0.0
    bound_ok = True
    if base_goodput is not None and goodput is not None:
        bound_ok = goodput >= base_goodput - 0.3
    failovers = int(stats.counter("fleet.failovers").value)
    dead = sum(1 for r in router.replicas if r.dead)
    sites = sorted({f["site"] for f in inj.fired})
    out = {
        "fleet_chaos_seed": seed,
        "fleet_chaos_survivor_parity": parity,
        "fleet_chaos_survivors": survivors,
        "fleet_chaos_lost": lost,
        "fleet_chaos_shed": shed,
        "fleet_chaos_request_errors": lost,
        "fleet_chaos_goodput": goodput,
        "fleet_chaos_goodput_bound_ok": int(bound_ok),
        "fleet_chaos_tokens_per_sec": round(
            sum(len(r.generated) for r in done.values()) / wall, 1)
        if wall > 0 else None,
        "fleet_chaos_failovers": failovers,
        "fleet_chaos_replicas_dead": dead,
        "fleet_chaos_hedges": int(
            stats.counter("fleet.hedges").value),
        "fleet_chaos_faults_injected": len(inj.fired),
        "fleet_chaos_sites_fired": sites,
        "fleet_chaos_wall_s": round(time.monotonic() - t0, 3),
    }
    out.update({f"fleet_chaos_{k}": v for k, v in tele_out.items()})
    out["fleet_chaos_alert_fired"] = int(
        stats.counter("alert.fired").value)
    # the acceptance pins: zero admitted requests lost, survivor
    # parity, exactly the killed replica died (a second death means
    # the hang overshot and the run proved nothing), >=5 sites
    ok = (parity == 1.0 and lost == 0 and bound_ok
          and failovers >= 1 and dead == 1 and len(sites) >= 5)
    return out, ok


def _drive_arm(args, disagg=None):
    """One measured rep of the --disagg comparison: build a fresh
    fleet (symmetric when ``disagg is None``, role-split otherwise),
    warm it, drive the seeded workload once, and reduce to the
    latency/goodput scalars ``run_disagg`` aggregates across reps.
    Every rep regenerates the request set from ``args.seed`` so all
    reps of both arms replay the identical arrival process."""
    rng = np.random.RandomState(args.seed)
    router, lens = build_fleet(args, disagg=disagg)
    reqs, prefixes = make_fleet_requests(args, lens, rng)
    if args.tenants:
        reqs = _assign_tenants(reqs, args, rng)
    if not args.no_warmup:
        _fleet_warm(router, args, lens, prefixes)
    wall, rids = drive_fleet(router, reqs, args.max_new,
                             deadline_ms=args.deadline_ms)
    done = router.results()
    finished = [done[r] for r in rids if r is not None]
    lost = sum(1 for r in rids if r is not None
               and getattr(done.get(r), "state", None) != "ok")
    ttfts = np.array([r.ttft_s for r in finished
                      if r.ttft_s is not None], np.float64) * 1e3
    if ttfts.size == 0:
        ttfts = np.array([0.0])
    judged = [r for r in finished
              if getattr(r, "slo_ok", None) is not None]
    goodput = round(sum(1 for r in judged if r.slo_ok)
                    / len(judged), 4) if judged else None
    return {"router": router,
            "p50": float(np.percentile(ttfts, 50)),
            "p99": float(np.percentile(ttfts, 99)),
            "tps": sum(len(r.generated) for r in finished) / wall
            if wall > 0 else None,
            "goodput": goodput, "lost": lost,
            "requests": len(finished)}


def run_disagg(args):
    """The --fleet --disagg bench (ISSUE 20): the SAME seeded
    prefill-heavy skewed Poisson workload driven twice — first on the
    symmetric fleet (every replica prefills AND decodes; the standard
    ``fleet_*`` keys), then on the role-split fleet (half the replicas
    prefill-specialized with the host-DRAM KV tier armed; finished
    prefills hand their KV to decode replicas over the migration
    path). Each arm runs ``--disagg-reps`` measured drives (fresh
    fleet per rep, identical seeded arrivals) and reports the MEDIAN
    across reps. Emits ``serve_disagg_*`` + ``fleet_spill_*`` keys
    and pins the acceptance: disagg median TTFT p99 <= symmetric,
    goodput >= symmetric, >=1 handoff actually streamed, zero
    requests lost in any disagg rep.

    CPU rung targets (bench.py --fleet-disagg, 2 replicas, prompt mix
    48,128,256): serve_disagg_p99_ttft_ms <= fleet_p99_ttft_ms,
    serve_disagg_goodput >= fleet_goodput, handoffs >= 1. TPU targets
    (v5e-8, 2 replicas, prompt mix 2048,8192,16384, rate 32):
    serve_disagg_p99_ttft_ms <= 0.7 * fleet_p99_ttft_ms and
    serve_disagg_tokens_per_sec >= 0.95 * fleet_tokens_per_sec — the
    decode fleet never pays a prefill stall, so the TTFT tail
    collapses while throughput holds."""
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.profiler import stats

    if args.prompt_mix == "8,32,96":
        # prefill-heavy skew: long prompts + a hot arrival burst make
        # prefill the contended resource the role split relieves
        args.prompt_mix = "48,128,256"
    if args.tpot_weight == 1.0:
        # production decode-SLO pressure, applied to BOTH runs: the
        # symmetric fleet must interleave decode AHEAD of queued
        # prefills (burst 4:1 from the weight ratio) — the TTFT tax
        # disaggregation deletes, since its prefill replicas override
        # to 8:1 and hand finished slots to the decode side instead
        # of decoding them here
        args.tpot_weight = 4.0
    out, ok = run_fleet(args)          # symmetric baseline
    reps = max(1, int(getattr(args, "disagg_reps", 1)))
    sym_extra = [_drive_arm(args, disagg=None)
                 for _ in range(reps - 1)]
    stats.reset()
    # host tier + CPU-calibrated cost model land BEFORE the disagg
    # engines construct (the tier is wired at __init__). The toy
    # CPU model's per-token prefill cost is ~1e8x smaller than a real
    # chip's, so the re-prefill arm of the directory cost model is
    # priced at a matching tiny TFLOP rate — otherwise restores would
    # never win and the pull path would sit unexercised.
    set_flags({"kv_host_tier_bytes": int(args.host_tier_bytes),
               "disagg_prefill_tflops": 1e-4})
    try:
        dis = [_drive_arm(args, disagg="auto") for _ in range(reps)]
    finally:
        set_flags({"kv_host_tier_bytes": 0,
                   "disagg_prefill_tflops": 100.0})
    # median across reps on BOTH arms: one measured drive per rep,
    # identical seeded workload, fresh fleet each time. A single
    # 12-24-sample p99 is the max order statistic and on a 1-core
    # host GIL scheduling noise swings it by 2x run-to-run — the
    # median rep is the comparison the pin can hold
    sym_p99 = [out["fleet_p99_ttft_ms"]] + [r["p99"] for r in sym_extra]
    sym_gp = [g for g in [out["fleet_goodput"]]
              + [r["goodput"] for r in sym_extra] if g is not None]
    out["fleet_p99_ttft_ms"] = round(float(np.median(sym_p99)), 3)
    if sym_gp:
        out["fleet_goodput"] = round(float(np.median(sym_gp)), 4)
    lost = sum(r["lost"] for r in dis)
    dis_gp = [r["goodput"] for r in dis if r["goodput"] is not None]
    goodput = round(float(np.median(dis_gp)), 4) if dis_gp else None
    c = stats.counter
    handoffs = int(c("fleet.handoffs").value)
    router = dis[-1]["router"]
    out.update({
        "serve_disagg_replicas": f"{router.disagg[0]}P:"
        f"{router.disagg[1]}D",
        "serve_disagg_reps": reps,
        "serve_disagg_p50_ttft_ms": round(
            float(np.median([r["p50"] for r in dis])), 3),
        "serve_disagg_p99_ttft_ms": round(
            float(np.median([r["p99"] for r in dis])), 3),
        "serve_disagg_tokens_per_sec": round(
            float(np.median([r["tps"] for r in dis
                             if r["tps"] is not None] or [0.0])), 1),
        "serve_disagg_goodput": goodput,
        "serve_disagg_requests": dis[-1]["requests"],
        "serve_disagg_lost": lost,
        "serve_disagg_handoffs": handoffs,
        "serve_disagg_handoff_pages": int(
            c("fleet.handoff_pages").value),
        "fleet_spill_pages": int(c("fleet.spills").value),
        "fleet_spill_bytes": int(c("fleet.spill_bytes").value),
        "fleet_restore_pages": int(c("fleet.restores").value),
        "fleet_restore_bytes": int(c("fleet.restore_bytes").value),
        "fleet_host_evictions": int(c("fleet.host_evictions").value),
        "fleet_directory_hits": int(c("fleet.directory_hits").value),
        "fleet_directory_pulls": int(
            c("fleet.directory_pulls").value),
        "fleet_directory_misses": int(
            c("fleet.directory_misses").value),
    })
    base_p99 = out.get("fleet_p99_ttft_ms")
    base_goodput = out.get("fleet_goodput")
    pins_ok = (handoffs >= 1 and lost == 0
               and (base_p99 is None
                    or out["serve_disagg_p99_ttft_ms"] <= base_p99)
               and (base_goodput is None or goodput is None
                    or goodput >= base_goodput))
    return out, ok and pins_ok


def run_lora(args):
    """The --adapters bench (ISSUE 18): one AdapterBank serving K
    distinct LoRA adapters, requests stamped round-robin so every
    decode chunk mixes adapters. Drives the SAME Poisson workload
    twice on one warm engine — single-tenant (every request on one
    adapter: identical adaptered programs, no grouping spread) then
    multi-adapter — and reports the throughput ratio as
    ``serve_lora_pct_of_single_tenant``. The compiled decode-program
    count is emitted too: it must not scale with the adapter set."""
    from paddle_tpu.profiler import stats
    from paddle_tpu.serving import AdapterBank

    rng = np.random.RandomState(args.seed)
    eng, lens = build_engine(args)
    bank = AdapterBank.from_stack(eng.model.stack._stack(),
                                  slots=args.adapters,
                                  rank=args.adapter_rank)
    for i in range(args.adapters):
        bank.load(bank.random_adapter(f"lora{i}", seed=args.seed + i,
                                      rank=args.adapter_rank))
    eng.adapters = bank
    swaps_warm = int(stats.counter("lora.swaps").value)
    reqs = make_requests(args, lens, rng)

    def reset():
        eng.finished.clear()
        eng.action_log.clear()
        eng.slo_monitor.reset()
        if eng.journal is not None:
            eng.journal.clear()
        if eng.usage is not None:
            eng.usage.reset()

    if not args.no_warmup:
        # compile every adaptered chunk/decode program (plus the
        # base-path ones a mixed batch would touch) outside both
        # measured windows, so the single-vs-multi ratio compares
        # steady states
        warm = [(np.full((L,), 1, np.int32), 0.0, None, "lora0")
                for L in lens]
        warm.append((np.full((lens[0],), 1, np.int32), 0.0))
        drive(eng, warm, args.max_new)
        reset()
        stats.reset()

    # single-tenant baseline: the whole load on ONE adapter
    wall_s, rids_s = drive(
        eng, [(p, g, None, "lora0") for p, g in reqs], args.max_new)
    single_tokens = sum(len(r.generated) for r in eng.finished)
    single_tps = single_tokens / wall_s if wall_s > 0 else 0.0
    reset()

    # multi-adapter run: round-robin over the full bank
    multi = [(p, g, None, f"lora{i % args.adapters}")
             for i, (p, g) in enumerate(reqs)]
    sampler = _start_telemetry(args, journal=eng.journal)
    wall_m, rids_m = drive(eng, multi, args.max_new)
    tele_out = _stop_telemetry(sampler, args.telemetry_out)
    done = eng.finished
    ttfts = np.array([r.ttft_s for r in done
                      if r.ttft_s is not None], np.float64) * 1e3
    if ttfts.size == 0:
        ttfts = np.array([0.0])
    multi_tokens = sum(len(r.generated) for r in done)
    multi_tps = multi_tokens / wall_m if wall_m > 0 else 0.0
    judged = [r for r in done if getattr(r, "slo_ok", None) is not None]
    goodput = round(sum(1 for r in judged if r.slo_ok)
                    / len(judged), 4) if judged else None
    if args.journal_out and eng.journal is not None:
        eng.journal.dump_jsonl(args.journal_out)
    _dump_usage(args, eng=eng)
    out = {
        "serve_lora_adapters": args.adapters,
        "serve_lora_rank": args.adapter_rank,
        "serve_lora_tokens_per_sec": round(multi_tps, 1),
        "serve_lora_single_tenant_tokens_per_sec": round(single_tps, 1),
        "serve_lora_pct_of_single_tenant": round(
            multi_tps / single_tps, 4) if single_tps > 0 else None,
        "serve_lora_swap_count": swaps_warm
        + int(stats.counter("lora.swaps").value),
        "serve_lora_grouped_launches": int(
            stats.counter("lora.grouped_launches").value),
        "serve_lora_decode_programs": len(eng._gen._decode_k_jit),
        "serve_lora_p50_ttft_ms": round(
            float(np.percentile(ttfts, 50)), 3),
        "serve_lora_p99_ttft_ms": round(
            float(np.percentile(ttfts, 99)), 3),
        "serve_lora_goodput": goodput,
        "serve_lora_requests": len(done),
        "serve_lora_shed": sum(1 for r in rids_m if r is None),
        "serve_lora_wall_s": round(wall_m, 3),
        "telemetry": _telemetry(),
    }
    out.update(_alert_keys())
    out.update(_usage_keys(eng=eng))
    out.update(tele_out)
    # the acceptance pin: batched multi-LoRA keeps >= 80% of the
    # single-tenant throughput (the grouped delta launch is ONE kernel
    # regardless of how many adapters the chunk mixes)
    ok = out["serve_lora_pct_of_single_tenant"] is not None \
        and out["serve_lora_pct_of_single_tenant"] >= 0.8
    return out, ok


def chaos_injector(seed):
    """The seeded chaos schedule: >=5 distinct serving-hot-path sites
    (kv.grow, prefill.dispatch, decode.step, prefix.insert,
    journal.dump) across every fault kind — raises, a delay, a token
    corruption (detected, never streamed), a pool squeeze that drives
    the REAL pool-pressure recovery paths, and an injected dump
    failure proving a crash dump can't mask an original error."""
    from paddle_tpu.serving import FaultInjector

    return (FaultInjector(seed=seed)
            .add("kv.grow", kind="raise", at=2)
            .add("prefill.dispatch", kind="raise", at=1)
            .add("prefill.dispatch", kind="delay", every=13, times=2,
                 delay_ms=2.0)
            .add("decode.step", kind="raise", at=3)
            .add("decode.step", kind="corrupt", at=6)
            .add("decode.step", kind="squeeze", pages=4, at=8)
            .add("decode.step", kind="release", at=16)
            .add("prefix.insert", kind="raise", at=1)
            .add("journal.dump", kind="raise", at=0))


def run_chaos(args, reqs, base_rids, base_done, base_goodput):
    """Re-drive the measured workload against a fresh engine with the
    seeded fault schedule armed (after a fault-free warmup, so compile
    time stays out of the SLO comparison). Returns
    ``(serve_chaos_* dict, ok: bool)``."""
    from paddle_tpu.profiler import stats

    seed = args.chaos_seed if args.chaos_seed is not None \
        else args.seed
    inj = chaos_injector(seed)
    eng, lens = build_engine(args)
    if not args.no_warmup:
        warm = [(np.full((L,), 1, np.int32), 0.0) for L in lens]
        drive(eng, warm, args.max_new)
        eng.finished.clear()
        eng.slo_monitor.reset()
        if eng.journal is not None:
            eng.journal.clear()
    eng.install_faults(inj)
    sampler = _start_telemetry(args, journal=eng.journal)
    t0 = time.monotonic()
    wall, rids = drive(eng, reqs, args.max_new,
                       deadline_ms=args.deadline_ms)
    tele_out = _stop_telemetry(
        sampler, args.telemetry_out + ".chaos"
        if args.telemetry_out else None)
    done_by_id = {r.id: r for r in eng.finished}
    base_by_id = {r.id: r for r in base_done}
    # survivor parity: every request the chaos run finished in the
    # "ok" state must carry exactly the fault-free run's greedy tokens
    # (keyed by submission index — ids differ between engines)
    survivors = mismatches = 0
    failed = {"error": 0, "deadline_exceeded": 0, "shed": 0}
    for idx, rid in enumerate(rids):
        if rid is None:
            failed["shed"] += 1
            continue
        req = done_by_id.get(rid)
        if req is None:
            continue
        state = getattr(req, "state", None)
        if state == "ok":
            survivors += 1
            brid = base_rids[idx] if idx < len(base_rids) else None
            base = base_by_id.get(brid) if brid is not None else None
            if base is not None and \
                    list(base.generated) != list(req.generated):
                mismatches += 1
        elif state in failed:
            failed[state] += 1
        else:
            failed["error"] += 1
    n = max(len(rids), 1)
    judged = [r for r in done_by_id.values()
              if getattr(r, "slo_ok", None) is not None]
    goodput = round(sum(1 for r in judged if r.slo_ok)
                    / len(judged), 4) if judged else None
    total_tokens = sum(len(r.generated) for r in done_by_id.values())
    parity = 1.0 if mismatches == 0 and survivors > 0 else 0.0
    n_failed = sum(failed.values())
    # pinned goodput bound: losing goodput beyond the failed share
    # plus slack means the faults degraded SURVIVORS too
    bound_ok = True
    if base_goodput is not None and goodput is not None:
        bound_ok = goodput >= base_goodput - n_failed / n - 0.25
    # forensic dump with the journal.dump fault armed: must swallow
    # the injected failure and return None rather than raise
    dump_survived = 1
    try:
        eng.crash_dump(error=None)
    except BaseException:
        dump_survived = 0
    sites = sorted({f["site"] for f in inj.fired})
    out = {
        "serve_chaos_seed": seed,
        "serve_chaos_survivor_parity": parity,
        "serve_chaos_survivors": survivors,
        "serve_chaos_request_errors": failed["error"],
        "serve_chaos_deadline_exceeded": failed["deadline_exceeded"],
        "serve_chaos_shed": failed["shed"],
        "serve_chaos_goodput": goodput,
        "serve_chaos_goodput_bound_ok": int(bound_ok),
        "serve_chaos_tokens_per_sec": round(total_tokens / wall, 1)
        if wall > 0 else None,
        "serve_chaos_faults_injected": len(inj.fired),
        "serve_chaos_sites_fired": sites,
        "serve_chaos_step_retries": int(
            stats.counter("serving.step_retries").value),
        "serve_chaos_dump_survived": dump_survived,
        "serve_chaos_wall_s": round(time.monotonic() - t0, 3),
    }
    out.update({f"serve_chaos_{k}": v for k, v in tele_out.items()})
    ok = (parity == 1.0 and bound_ok and dump_survived == 1
          and len(sites) >= 5)
    return out, ok


def main():
    ap = argparse.ArgumentParser(
        description="Poisson-load serving benchmark (SLO rungs)")
    ap.add_argument("--streams", type=int, default=8,
                    help="decode slots (max_batch)")
    ap.add_argument("--requests", type=int, default=None,
                    help="total requests (default 3*streams)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rate", type=float, default=100.0,
                    help="Poisson arrival rate, requests/sec")
    ap.add_argument("--prompt-mix", default="8,32,96",
                    help="comma list of prompt lengths, sampled "
                         "uniformly")
    ap.add_argument("--system-prompt", type=int, default=32,
                    help="shared system-prompt tokens prepended to a "
                         "fraction of requests (0 disables)")
    ap.add_argument("--system-frac", type=float, default=0.5)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--prefill-chunk", type=int, default=64)
    ap.add_argument("--decode-chunk", type=int, default=8)
    ap.add_argument("--ttft-weight", type=float, default=1.0)
    ap.add_argument("--tpot-weight", type=float, default=1.0)
    ap.add_argument("--ttft-target", type=float, default=1000.0,
                    help="SLO TTFT target (ms) for per-request "
                         "verdicts and serve_goodput")
    ap.add_argument("--tpot-target", type=float, default=100.0,
                    help="SLO TPOT target (ms)")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="per-request deadline from arrival; exceeded "
                         "-> the request aborts in the "
                         "deadline_exceeded terminal state")
    ap.add_argument("--speculative", action="store_true",
                    help="speculative decoding: the scheduler's decode "
                         "slot runs draft+verify rounds instead of "
                         "token-by-token chunks; every serve_* key "
                         "re-emits as serve_spec_* plus "
                         "serve_spec_accept_rate (bench_gate gates "
                         "throughput/accept down, TTFT up)")
    ap.add_argument("--spec-k", type=int, default=None,
                    help="draft window (default: FLAGS_spec_k)")
    ap.add_argument("--spec-drafter", default="self",
                    choices=["self", "draft", "oracle"],
                    help="self = training-free self-draft heads; "
                         "draft = quarter-size draft model; oracle = "
                         "the target model as its own drafter (accept "
                         "rate 1.0 — the amortization ceiling)")
    ap.add_argument("--long-context", action="store_true",
                    help="long-context serving rung (ISSUE 13): "
                         "defaults the prompt mix to long prompts so "
                         "chunked prefill attends deep into the paged "
                         "pool through the in-place varlen kernel; "
                         "every serve_* key re-emits as serve_long_* "
                         "(gated by bench_gate: TTFT UP, tokens/s "
                         "DOWN)")
    ap.add_argument("--fleet", type=int, default=0,
                    help="fleet mode (ISSUE 14): route the load "
                         "through a FleetRouter over N replicas (one "
                         "serve-loop thread each); emits fleet_* keys "
                         "instead of serve_*; composes with --chaos "
                         "(replica kill mid-load, zero-loss pins)")
    ap.add_argument("--drain-async", action="store_true",
                    help="with --fleet (ISSUE 19): mid-load, "
                         "gracefully drain replica 0 under "
                         "FLAGS_migrate_async — its mid-decode slots "
                         "stream complete KV pages to peers while "
                         "both endpoints keep decoding — and pin "
                         "migration-concurrent decode progress "
                         "(fleet_async_migration_* keys; nonzero "
                         "exit when no pages streamed, decode "
                         "stalled, or a request was lost)")
    ap.add_argument("--disagg", action="store_true",
                    help="with --fleet N: drive the workload on a "
                    "symmetric fleet, then again with a prefill/"
                    "decode role split + host-DRAM KV tier, and pin "
                    "that disaggregation beats the symmetric TTFT "
                    "p99 and goodput (ISSUE 20)")
    ap.add_argument("--host-tier-bytes", type=int, default=8 << 20,
                    help="per-replica host-DRAM KV tier capacity for "
                    "the --disagg run (FLAGS_kv_host_tier_bytes)")
    ap.add_argument("--disagg-reps", type=int, default=3,
                    help="measured drives per arm of the --disagg "
                    "comparison; the pin compares MEDIAN TTFT p99 "
                    "across reps (a single small-sample p99 is the "
                    "max order statistic — thread-scheduling noise "
                    "on a shared-core host swings it 2x run-to-run)")
    ap.add_argument("--fleet-policy", default="affinity",
                    choices=["affinity", "rr"],
                    help="dispatch policy: blake2b prefix-affinity + "
                         "load/SLO tie-break (default), or the "
                         "round-robin baseline it is pinned against")
    ap.add_argument("--system-prompts", type=int, default=4,
                    help="distinct system prompts in the fleet's "
                         "skewed-prefix load (Zipf-ish popularity; "
                         "each is --system-prompt tokens long)")
    ap.add_argument("--chaos", action="store_true",
                    help="re-drive the measured workload under a "
                         "seeded >=5-site fault schedule and pin "
                         "survivor token parity + bounded goodput "
                         "loss (serve_chaos_* keys; nonzero exit on "
                         "a failed pin)")
    ap.add_argument("--chaos-seed", type=int, default=None,
                    help="fault-schedule seed (default: --seed)")
    ap.add_argument("--adapters", type=int, default=0,
                    help="multi-LoRA workload (ISSUE 18): serve K "
                         "distinct adapters from one AdapterBank, "
                         "round-robin adapter_id per request; emits "
                         "serve_lora_* keys and pins "
                         "pct_of_single_tenant >= 0.8 (nonzero exit "
                         "on a failed pin)")
    ap.add_argument("--adapter-rank", type=int, default=8,
                    help="LoRA rank for the bench adapters (padded "
                         "to the bank's sublane tile)")
    ap.add_argument("--tenants", type=int, default=0,
                    help="multi-tenant workload (ISSUE 17): stamp a "
                         "Zipf-popular tenant id (K distinct) on "
                         "every request and turn the per-tenant "
                         "usage ledger on; emits serve_tenant_* and "
                         "usage_unattributed_ms (the latter gated UP "
                         "by bench_gate with no noise floor)")
    ap.add_argument("--tenant-skew", type=float, default=1.0,
                    help="Zipf exponent for tenant popularity "
                         "(rank k drawn ∝ 1/(k+1)^skew; 0 = uniform)")
    ap.add_argument("--usage-out", default=None,
                    help="dump the per-request usage JSONL "
                         "(serve_top --tenants / trace_merge input); "
                         "implies the usage ledger on; fleet runs "
                         "write <path>_r<idx>.jsonl per replica plus "
                         "<path>_router.jsonl")
    ap.add_argument("--requests-out", default=None,
                    help="write per-request JSONL (id, lens, waits, "
                         "ttft/tpot, preempt/requeue counts, slo_ok) "
                         "so offline analysis never re-derives from "
                         "histograms")
    ap.add_argument("--journal-out", default=None,
                    help="dump the flight-recorder journal JSONL "
                         "(tools/serve_top.py input)")
    ap.add_argument("--telemetry-out", default=None,
                    help="continuous telemetry (ISSUE 16): sample "
                         "the stats registry on a background "
                         "TimeSeriesSampler with the default alert "
                         "rules armed during the measured run and "
                         "dump the time-series JSONL here "
                         "(serve_top --history input); a --chaos "
                         "re-drive dumps its own series to "
                         "<path>.chaos")
    ap.add_argument("--telemetry-interval-ms", type=float,
                    default=50.0,
                    help="sampling interval for --telemetry-out")
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--bf16", action="store_true",
                    help="cast the stack bf16 (the chip serving dtype)")
    ap.add_argument("--quant", default=None,
                    choices=[None, "int8", "a8w8"])
    ap.add_argument("--no-warmup", action="store_true",
                    help="measure cold compiles inside the TTFTs")
    ap.add_argument("--mp", type=int, default=0,
                    help="tensor-parallel degree: shard the serving "
                         "stack over an mp mesh of that many devices "
                         "(rung keys become serve_tp{N}_*); on a CPU "
                         "run virtual devices are provisioned")
    ap.add_argument("--no-lint", action="store_true",
                    help="skip the tpu_lint preflight gate")
    args = ap.parse_args()
    if args.long_context and args.prompt_mix == "8,32,96":
        # CPU-sized long mix (a chip run passes its own, e.g.
        # 2048,8192,16384 via bench.py --serve-long); long prompts +
        # a modest rate keep the run prefill-dominated
        args.prompt_mix = "64,256,768"
        args.rate = min(args.rate, 16.0)
    if args.requests is None:
        args.requests = 3 * args.streams

    import os

    if args.mp and args.mp > 1 and "jax" not in sys.modules \
            and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # CPU runs (CI) get virtual devices for the mp mesh; must land
        # before the first jax import (backend init reads XLA_FLAGS)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.mp}"
        ).strip()

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.analysis.preflight import preflight

    preflight("serve_bench", no_lint=args.no_lint)

    if args.tenants or args.usage_out:
        # must land before any engine/router is constructed — the
        # ledger is wired (or not) at __init__
        from paddle_tpu.core.flags import set_flags

        set_flags({"usage_ledger": True})

    from paddle_tpu.profiler import stats

    if args.adapters:
        out, lora_ok = run_lora(args)
        print(json.dumps(out))
        if not lora_ok:
            print("serve_bench --adapters: batched multi-LoRA pin "
                  "FAILED (serve_lora_pct_of_single_tenant < 0.8 — "
                  "the grouped delta path is paying per-adapter "
                  "cost)", file=sys.stderr)
            sys.exit(1)
        return

    if args.fleet and args.fleet > 1 and args.disagg:
        out, disagg_ok = run_disagg(args)
        print(json.dumps(out))
        if not disagg_ok:
            print("serve_bench --disagg: acceptance pins FAILED "
                  "(no prefill->decode handoff streamed, a request "
                  "was lost, or the disaggregated fleet did not "
                  "beat the symmetric fleet's TTFT p99 / goodput)",
                  file=sys.stderr)
            sys.exit(1)
        return

    if args.fleet and args.fleet > 1:
        out, fleet_ok = run_fleet(args)
        print(json.dumps(out))
        if not fleet_ok:
            print("serve_bench --fleet: acceptance pins FAILED "
                  "(--chaos: survivor parity / lost requests / "
                  "goodput bound / failover+death accounting / site "
                  "coverage; --drain-async: no async migration "
                  "streamed, decode made no progress during the "
                  "drain, or a request was lost)", file=sys.stderr)
            sys.exit(1)
        return

    eng, lens = build_engine(args)
    rng = np.random.RandomState(args.seed)

    if not args.no_warmup:
        # compile every chunk/decode program shape OUTSIDE the
        # measured window (steady-state SLO; --no-warmup for the
        # cold-start view), then reset telemetry so the measured block
        # describes only the load run
        warm = [(np.full((L,), 1, np.int32), 0.0) for L in lens]
        if args.system_prompt:
            warm.append((np.full(
                (args.system_prompt + lens[0],), 1, np.int32), 0.0))
        drive(eng, warm, args.max_new)
        eng.finished.clear()
        eng.action_log.clear()
        eng.slo_monitor.reset()
        if eng.journal is not None:
            eng.journal.clear()  # the journal describes the load run
        if eng.usage is not None:
            eng.usage.reset()    # so does the usage ledger
        stats.reset()

    reqs = make_requests(args, lens, rng)
    if args.tenants:
        reqs = _assign_tenants(reqs, args, rng)
    sampler = _start_telemetry(args, journal=eng.journal)
    wall, rids = drive(eng, reqs, args.max_new,
                       deadline_ms=args.deadline_ms)
    tele_out = _stop_telemetry(sampler, args.telemetry_out)

    done = eng.finished
    if eng.journal is not None:
        eng.journal.publish_gauges()
    ttfts = np.array([r.ttft_s for r in done
                      if r.ttft_s is not None], np.float64) * 1e3
    if ttfts.size == 0:
        ttfts = np.array([0.0])
    tpots = [r.tpot_s for r in done if r.tpot_s is not None]
    total_tokens = sum(len(r.generated) for r in done)
    # SLO goodput over the WHOLE run (not the monitor's rolling
    # window): fraction of finished requests whose stamped verdict
    # met both targets — bench_gate gates this (direction "down")
    judged = [r for r in done if getattr(r, "slo_ok", None) is not None]
    goodput = round(sum(1 for r in judged if r.slo_ok) / len(judged), 4) \
        if judged else None
    if args.requests_out:
        with open(args.requests_out, "w") as f:
            for r in sorted(done, key=lambda r: r.id):
                f.write(json.dumps({
                    "id": r.id,
                    "prompt_len": int(len(r.prompt)),
                    "new_tokens": len(r.generated),
                    "queue_wait_ms": None if r.queue_wait_s is None
                    else round(r.queue_wait_s * 1e3, 3),
                    "ttft_ms": None if r.ttft_s is None
                    else round(r.ttft_s * 1e3, 3),
                    "tpot_ms": None if r.tpot_s is None
                    else round(r.tpot_s * 1e3, 3),
                    "preempts": getattr(r, "n_preempts", 0),
                    "requeues": getattr(r, "n_requeues", 0),
                    "slo_ok": getattr(r, "slo_ok", None),
                    "state": getattr(r, "state", None),
                    "error": None if getattr(r, "error", None) is None
                    else type(r.error).__name__,
                }) + "\n")
    if args.journal_out and eng.journal is not None:
        eng.journal.dump_jsonl(args.journal_out)
    if eng.usage is not None:
        eng.usage.publish_gauges()
    _dump_usage(args, eng=eng)
    out = {
        "serve_p50_ttft_ms": round(float(np.percentile(ttfts, 50)), 3),
        "serve_p99_ttft_ms": round(float(np.percentile(ttfts, 99)), 3),
        "serve_tokens_per_sec": round(total_tokens / wall, 1),
        "serve_p50_tpot_ms": round(
            float(np.median(tpots)) * 1e3, 3) if tpots else None,
        "serve_goodput": goodput,
        "serve_ttft_target_ms": args.ttft_target,
        "serve_tpot_target_ms": args.tpot_target,
        "serve_preemptions": int(
            stats.counter("serving.preemptions").value),
        "serve_streams": args.streams,
        "serve_requests": len(done),
        "serve_rate": args.rate,
        "serve_prompt_mix": args.prompt_mix,
        "serve_prefill_chunk": args.prefill_chunk,
        "serve_decode_chunk": eng.decode_chunk,
        "serve_prefix_hits": int(
            stats.counter("serving.prefix_hit").value),
        "serve_prefix_pages_saved": int(
            stats.counter("serving.prefix_pages_saved").value),
        "serve_wall_s": round(wall, 3),
        "telemetry": _telemetry(),
    }
    out.update(_alert_keys())
    out.update(_usage_keys(eng=eng))
    out.update(tele_out)
    chaos_ok = True
    if args.chaos:
        chaos_out, chaos_ok = run_chaos(args, reqs, rids, done,
                                        goodput)
        out.update(chaos_out)
    if args.speculative:
        # speculative rung keys: serve_spec_* so bench_gate tracks the
        # draft+verify SLO rungs independently of the plain serve_*
        # ones; accept rate is the amortization health signal (gated
        # DOWN — a drafter regression shows here before throughput)
        drafted = int(
            stats.counter("serving.spec_drafted_tokens").value)
        accepted = int(
            stats.counter("serving.spec_accepted_tokens").value)
        out["serve_accept_rate"] = round(accepted / drafted, 4) \
            if drafted else None
        out["serve_rounds"] = int(
            stats.counter("serving.spec_rounds").value)
        out["serve_drafter"] = args.spec_drafter
        out["serve_k"] = int(eng._spec.k)
        out = {(f"serve_spec_{k[len('serve_'):]}"
                if k.startswith("serve_") else k): v
               for k, v in out.items()}
    if args.long_context:
        # long-context rung keys: serve_long_* so bench_gate tracks
        # the varlen-prefill SLO rungs independently of the short-mix
        # serve_* ones
        out = {(f"serve_long_{k[len('serve_'):]}"
                if k.startswith("serve_") else k): v
               for k, v in out.items()}
    if args.mp and args.mp > 1:
        # TP rung keys: serve_tp{N}_* so bench_gate tracks the
        # mp-sharded SLO rungs independently of the mp1 ones (whose
        # preservation the gate checks on the plain serve_* keys)
        out = {(f"serve_tp{args.mp}_" + k[len("serve_"):]
                if k.startswith("serve_") else k): v
               for k, v in out.items()}
        out["serve_mp_degree"] = args.mp
    print(json.dumps(out))
    if not chaos_ok:
        print("serve_bench --chaos: robustness pins FAILED "
              "(survivor parity / goodput bound / dump survival / "
              "site coverage)", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
