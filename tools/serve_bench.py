"""Poisson-load serving benchmark: SLO numbers for the serving frontend.

Drives ``paddle_tpu.serving.ServingEngine`` the way traffic does — a
seeded Poisson arrival process submits N concurrent streams of mixed
prompt lengths from a background thread while the scheduler loop runs
— and prints ONE JSON line with the SLO rungs ``tools/bench_gate.py``
gates (TTFT regresses UP, throughput DOWN):

    python tools/serve_bench.py --streams 8 --seed 0

    {"serve_p50_ttft_ms": ..., "serve_p99_ttft_ms": ...,
     "serve_tokens_per_sec": ..., "serve_goodput": ...,
     ..., "telemetry": {...}}

``serve_goodput`` is the fraction of finished requests meeting BOTH
the ``--ttft-target`` and ``--tpot-target`` SLOs (verdicts stamped
per request by serving/slo.py). ``--requests-out`` writes one JSONL
row per request (waits/ttft/tpot/preempt counts/verdict) and
``--journal-out`` dumps the flight recorder for
``tools/serve_top.py`` forensics.

Defaults are CPU-sized (tiny model) so the rung runs in CI; on a chip
pass the 1.3B geometry (--d-model 2048 --layers 24 --heads 16
--vocab 51200) and a rate that saturates it. A warmup pass compiles
every chunk/decode program first (--no-warmup to include compiles in
the measured TTFTs — the cold-start view).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def _telemetry():
    """Runtime-telemetry block (the bench.py shape): stats registry
    snapshot + the per-program roofline table, so the serve rungs
    carry the serve.{ttft,tpot,queue_wait} histograms and the
    per-phase ``serve.prefill[c=*]`` / ``decode.*[k=*]`` rows."""
    from paddle_tpu.profiler import roofline, stats

    snap = stats.snapshot()
    out = {
        "counters": {k: v for k, v in snap["counters"].items()
                     if not k.startswith("op.")},
        "gauges": snap["gauges"],
        "histograms": snap["histograms"],
    }
    rl = roofline.report()
    if rl:
        out["roofline"] = {k: v for k, v in rl.items()
                           if k.startswith(("serve", "decode",
                                            "prefill"))}
    return out


def build_engine(args):
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference import FusedCausalLM
    from paddle_tpu.serving import ServingEngine, SLOConfig

    paddle.seed(args.seed)
    lens = [int(x) for x in args.prompt_mix.split(",")]
    max_len = max(lens) + args.system_prompt + args.max_new + 1
    model = FusedCausalLM(
        vocab_size=args.vocab, embed_dim=args.d_model,
        num_heads=args.heads, dim_feedforward=4 * args.d_model,
        num_layers=args.layers, max_position=max_len + 1)
    if args.bf16:
        st = model.stack
        for n in ("qkv_weight", "qkv_bias", "out_weight", "out_bias",
                  "ffn1_weight", "ffn1_bias", "ffn2_weight",
                  "ffn2_bias"):
            p = getattr(st, n)
            p._rebind(p._data.astype(jnp.bfloat16))
    slo = SLOConfig(ttft_weight=args.ttft_weight,
                    tpot_weight=args.tpot_weight,
                    prefill_chunk=args.prefill_chunk,
                    ttft_target_ms=args.ttft_target,
                    tpot_target_ms=args.tpot_target)
    return ServingEngine(
        model, max_batch=args.streams, page_size=args.page_size,
        max_length=max_len, decode_chunk=args.decode_chunk,
        quant=args.quant, slo=slo,
        mp_degree=args.mp if args.mp and args.mp > 1 else None), lens


def make_requests(args, lens, rng):
    """(prompt, arrival_gap_s) list: mixed lengths, a shared system
    prompt on a fraction of requests (the prefix-cache's traffic
    shape), exponential inter-arrival gaps (Poisson process)."""
    sys_prompt = rng.randint(0, args.vocab, (args.system_prompt,)) \
        if args.system_prompt else None
    reqs = []
    for i in range(args.requests):
        L = int(lens[int(rng.randint(len(lens)))])
        body = rng.randint(0, args.vocab, (L,))
        if sys_prompt is not None and rng.rand() < args.system_frac:
            prompt = np.concatenate([sys_prompt, body])
        else:
            prompt = body
        gap = float(rng.exponential(1.0 / args.rate))
        reqs.append((prompt, gap))
    return reqs


def drive(eng, reqs, max_new):
    """Submit on a background thread at the Poisson arrival times;
    run the scheduler loop here until every request finishes."""
    n = len(reqs)
    err: list = []

    def submitter():
        try:
            t_next = time.monotonic()
            for prompt, gap in reqs:
                t_next += gap
                delay = t_next - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                eng.submit(prompt, max_new_tokens=max_new)
        except BaseException as e:  # surface on the main thread
            err.append(e)

    th = threading.Thread(target=submitter, daemon=True)
    t0 = time.monotonic()
    th.start()
    while len(eng.finished) < n:
        if err:
            raise err[0]
        if (eng._inbox or eng.waiting or eng._prefilling
                or eng.num_active):
            eng.step()
        else:
            time.sleep(0.0005)  # idle: wait for the next arrival
    th.join()
    return time.monotonic() - t0


def main():
    ap = argparse.ArgumentParser(
        description="Poisson-load serving benchmark (SLO rungs)")
    ap.add_argument("--streams", type=int, default=8,
                    help="decode slots (max_batch)")
    ap.add_argument("--requests", type=int, default=None,
                    help="total requests (default 3*streams)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rate", type=float, default=100.0,
                    help="Poisson arrival rate, requests/sec")
    ap.add_argument("--prompt-mix", default="8,32,96",
                    help="comma list of prompt lengths, sampled "
                         "uniformly")
    ap.add_argument("--system-prompt", type=int, default=32,
                    help="shared system-prompt tokens prepended to a "
                         "fraction of requests (0 disables)")
    ap.add_argument("--system-frac", type=float, default=0.5)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--prefill-chunk", type=int, default=64)
    ap.add_argument("--decode-chunk", type=int, default=8)
    ap.add_argument("--ttft-weight", type=float, default=1.0)
    ap.add_argument("--tpot-weight", type=float, default=1.0)
    ap.add_argument("--ttft-target", type=float, default=1000.0,
                    help="SLO TTFT target (ms) for per-request "
                         "verdicts and serve_goodput")
    ap.add_argument("--tpot-target", type=float, default=100.0,
                    help="SLO TPOT target (ms)")
    ap.add_argument("--requests-out", default=None,
                    help="write per-request JSONL (id, lens, waits, "
                         "ttft/tpot, preempt/requeue counts, slo_ok) "
                         "so offline analysis never re-derives from "
                         "histograms")
    ap.add_argument("--journal-out", default=None,
                    help="dump the flight-recorder journal JSONL "
                         "(tools/serve_top.py input)")
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--bf16", action="store_true",
                    help="cast the stack bf16 (the chip serving dtype)")
    ap.add_argument("--quant", default=None,
                    choices=[None, "int8", "a8w8"])
    ap.add_argument("--no-warmup", action="store_true",
                    help="measure cold compiles inside the TTFTs")
    ap.add_argument("--mp", type=int, default=0,
                    help="tensor-parallel degree: shard the serving "
                         "stack over an mp mesh of that many devices "
                         "(rung keys become serve_tp{N}_*); on a CPU "
                         "run virtual devices are provisioned")
    ap.add_argument("--no-lint", action="store_true",
                    help="skip the tpu_lint preflight gate")
    args = ap.parse_args()
    if args.requests is None:
        args.requests = 3 * args.streams

    import os

    if args.mp and args.mp > 1 and "jax" not in sys.modules \
            and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # CPU runs (CI) get virtual devices for the mp mesh; must land
        # before the first jax import (backend init reads XLA_FLAGS)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.mp}"
        ).strip()

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.analysis.preflight import preflight

    preflight("serve_bench", no_lint=args.no_lint)

    from paddle_tpu.profiler import stats

    eng, lens = build_engine(args)
    rng = np.random.RandomState(args.seed)

    if not args.no_warmup:
        # compile every chunk/decode program shape OUTSIDE the
        # measured window (steady-state SLO; --no-warmup for the
        # cold-start view), then reset telemetry so the measured block
        # describes only the load run
        warm = [(np.full((L,), 1, np.int32), 0.0) for L in lens]
        if args.system_prompt:
            warm.append((np.full(
                (args.system_prompt + lens[0],), 1, np.int32), 0.0))
        drive(eng, warm, args.max_new)
        eng.finished.clear()
        eng.action_log.clear()
        eng.slo_monitor.reset()
        if eng.journal is not None:
            eng.journal.clear()  # the journal describes the load run
        stats.reset()

    reqs = make_requests(args, lens, rng)
    wall = drive(eng, reqs, args.max_new)

    done = eng.finished
    if eng.journal is not None:
        eng.journal.publish_gauges()
    ttfts = np.array([r.ttft_s for r in done], np.float64) * 1e3
    tpots = [r.tpot_s for r in done if r.tpot_s is not None]
    total_tokens = sum(len(r.generated) for r in done)
    # SLO goodput over the WHOLE run (not the monitor's rolling
    # window): fraction of finished requests whose stamped verdict
    # met both targets — bench_gate gates this (direction "down")
    judged = [r for r in done if getattr(r, "slo_ok", None) is not None]
    goodput = round(sum(1 for r in judged if r.slo_ok) / len(judged), 4) \
        if judged else None
    if args.requests_out:
        with open(args.requests_out, "w") as f:
            for r in sorted(done, key=lambda r: r.id):
                f.write(json.dumps({
                    "id": r.id,
                    "prompt_len": int(len(r.prompt)),
                    "new_tokens": len(r.generated),
                    "queue_wait_ms": None if r.queue_wait_s is None
                    else round(r.queue_wait_s * 1e3, 3),
                    "ttft_ms": None if r.ttft_s is None
                    else round(r.ttft_s * 1e3, 3),
                    "tpot_ms": None if r.tpot_s is None
                    else round(r.tpot_s * 1e3, 3),
                    "preempts": getattr(r, "n_preempts", 0),
                    "requeues": getattr(r, "n_requeues", 0),
                    "slo_ok": getattr(r, "slo_ok", None),
                }) + "\n")
    if args.journal_out and eng.journal is not None:
        eng.journal.dump_jsonl(args.journal_out)
    out = {
        "serve_p50_ttft_ms": round(float(np.percentile(ttfts, 50)), 3),
        "serve_p99_ttft_ms": round(float(np.percentile(ttfts, 99)), 3),
        "serve_tokens_per_sec": round(total_tokens / wall, 1),
        "serve_p50_tpot_ms": round(
            float(np.median(tpots)) * 1e3, 3) if tpots else None,
        "serve_goodput": goodput,
        "serve_ttft_target_ms": args.ttft_target,
        "serve_tpot_target_ms": args.tpot_target,
        "serve_preemptions": int(
            stats.counter("serving.preemptions").value),
        "serve_streams": args.streams,
        "serve_requests": len(done),
        "serve_rate": args.rate,
        "serve_prompt_mix": args.prompt_mix,
        "serve_prefill_chunk": args.prefill_chunk,
        "serve_decode_chunk": eng.decode_chunk,
        "serve_prefix_hits": int(
            stats.counter("serving.prefix_hit").value),
        "serve_prefix_pages_saved": int(
            stats.counter("serving.prefix_pages_saved").value),
        "serve_wall_s": round(wall, 3),
        "telemetry": _telemetry(),
    }
    if args.mp and args.mp > 1:
        # TP rung keys: serve_tp{N}_* so bench_gate tracks the
        # mp-sharded SLO rungs independently of the mp1 ones (whose
        # preservation the gate checks on the plain serve_* keys)
        out = {(f"serve_tp{args.mp}_" + k[len("serve_"):]
                if k.startswith("serve_") else k): v
               for k, v in out.items()}
        out["serve_mp_degree"] = args.mp
    print(json.dumps(out))


if __name__ == "__main__":
    main()
