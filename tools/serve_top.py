"""serve_top: live/offline text dashboard over the serving journal.

Renders the serving frontend's flight recorder (serving/journal.py)
as a top-style dashboard — phase occupancy, queue depth, SLO goodput
and burn rate, pool-pressure counts, and the slowest requests with
their full event timelines — from a journal/crash JSONL artifact or a
running engine:

    python tools/serve_top.py serve_journal.jsonl
    python tools/serve_top.py /tmp/serve_crash_rank0_pid123.jsonl
    python tools/serve_top.py j.jsonl --req 17          # one timeline
    python tools/serve_top.py j.jsonl --export-trace t.json --rank 0
    python tools/serve_top.py j.jsonl --watch 2         # re-render
    python tools/serve_top.py j.jsonl --interval 2      # clock-seam watch
    python tools/serve_top.py --fleet j_r0.jsonl j_r1.jsonl  # fleet
    python tools/serve_top.py --history telemetry.jsonl # sparklines
    python tools/serve_top.py --tenants usage.jsonl     # per-tenant

``--tenants`` (ISSUE 17) renders the per-tenant usage table —
attributed device time + share, KV page-seconds, queue seconds,
token counts and the wasted-token share — from usage JSONL dumps
(``serve_bench --usage-out`` / ``UsageLedger.dump_jsonl`` /
``FleetRouter.export_usage``). Passing SEVERAL dumps folds them to
one record per request first (``accounting.fold_records`` — the
merged fleet tenant view: a failed-over request is charged once).
The live in-process forms are ``render_tenants_engine(engine)`` and
``render_fleet(router)`` (which appends the fleet tenant table when
the ledger is on).

``--history`` (ISSUE 16) renders sparkline views (goodput /
burn-rate / queue depth / throughput / phase occupancy, plus an
alert-marker row) over a continuous-telemetry series dump
(``TimeSeriesSampler.dump_jsonl`` / ``serve_bench
--telemetry-out``); combined with a journal argument it appends the
history below the dashboard. ``--interval`` is the watch cadence
routed through the serving clock seam (testable without sleeping).

``--fleet`` (ISSUE 14) takes one journal per replica
(``FleetRouter.export_journals``) and renders a per-replica
health/occupancy/goodput row plus the merged request-level view —
request ids are fleet-unique, so a failover/migration hop shows up on
every replica lane it touched. The live in-process form is
``serve_top.render_fleet(router)``.

Offline mode is stdlib-only — ``serving/journal.py`` is loaded
standalone, so a post-mortem over a crash dump never pays the
paddle_tpu/jax import. Live mode is the in-process API::

    from tools import serve_top
    print(serve_top.render_engine(engine))   # any running ServingEngine

Verdicts come from the journal's ``finish`` events when the SLO
monitor stamped them; ``--ttft-target/--tpot-target`` re-judge
offline journals that lack them. ``--export-trace`` writes the
one-lane-per-request chrome trace (rank-stamped: feed several ranks'
exports through ``tools/trace_merge.py`` for one fleet timeline).
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

__all__ = ["summarize", "render", "render_engine", "render_fleet",
           "render_fleet_offline", "render_history", "sparkline",
           "render_tenants", "render_tenants_engine", "main"]


def _journal_mod():
    """serving/journal.py loaded standalone (the module is stdlib-only
    at import time) so offline dashboards skip the jax import."""
    spec = importlib.util.spec_from_file_location(
        "_serve_journal", os.path.join(
            _REPO, "paddle_tpu", "serving", "journal.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _faults_mod():
    """serving/faults.py loaded standalone (also stdlib-only at
    import) — the watch loop sleeps through ITS clock seam, so tests
    drive ``--interval`` with a ManualClock instead of real sleeps."""
    spec = importlib.util.spec_from_file_location(
        "_serve_faults", os.path.join(
            _REPO, "paddle_tpu", "serving", "faults.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ts_mod():
    """profiler/timeseries.py loaded standalone (stdlib-only at
    import) — ``--history`` parses telemetry dumps with the writer's
    own loader."""
    spec = importlib.util.spec_from_file_location(
        "_serve_timeseries", os.path.join(
            _REPO, "paddle_tpu", "profiler", "timeseries.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _accounting_mod():
    """serving/accounting.py loaded standalone (stdlib-only at
    import) — ``--tenants`` folds usage JSONL dumps without paying
    the jax import."""
    spec = importlib.util.spec_from_file_location(
        "_serve_accounting", os.path.join(
            _REPO, "paddle_tpu", "serving", "accounting.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def summarize(events: List[dict], ttft_target: Optional[float] = None,
              tpot_target: Optional[float] = None,
              objective: float = 0.99) -> dict:
    """Fold a journal event stream into dashboard state: per-request
    phase/readings/verdicts plus engine-level pressure counts."""
    reqs: dict = {}
    counts = {"preempt": 0, "requeue": 0, "stall": 0, "error": 0,
              "deadline_exceeded": 0, "shed": 0, "retry": 0,
              "watchdog": 0, "fault": 0, "failover": 0, "migrate": 0,
              "drain": 0, "handoff": 0}
    evicted_pages = spilled_pages = restored_pages = 0
    spec_rounds = spec_drafted = spec_accepted = 0
    alerts_fired = alerts_resolved = 0
    alerts_active: set = set()
    for e in events:
        ev = e.get("ev")
        rid = int(e.get("rid", -1))
        if ev == "evict_trigger":
            evicted_pages += int(e.get("pages", 0))
        if ev == "spill":
            # ISSUE 20: KV pages demoted to the host-DRAM tier
            # (rid=-1 — spills belong to pool pressure, not a request)
            spilled_pages += int(e.get("pages", 0))
        if ev == "restore":
            restored_pages += int(e.get("pages", 0))
        if ev == "spec_verify":
            spec_rounds += 1
            spec_drafted += int(e.get("k", 0))
            spec_accepted += int(e.get("accepted", 0))
        if ev == "alert":
            # ISSUE 16: telemetry alert-rule transitions (rid=-1 —
            # alerts belong to the serve, not one request)
            name = e.get("name", "?")
            if e.get("state") == "firing":
                alerts_fired += 1
                alerts_active.add(name)
            else:
                alerts_resolved += 1
                alerts_active.discard(name)
        if ev in counts:
            counts[ev] += 1
        if rid < 0:
            continue
        r = reqs.setdefault(rid, {
            "rid": rid, "events": [], "phase": "waiting",
            "ttft_ms": None, "tpot_ms": None, "slo_ok": None,
            "preempts": 0, "requeues": 0, "stalls": 0,
            "prompt_len": None, "n_tokens": None, "chunks": 0,
            "adapter": None})
        r["events"].append(e)
        if ev == "submit":
            r["prompt_len"] = e.get("prompt_len")
            r["adapter"] = e.get("adapter")
        elif ev == "queued":
            r["phase"] = "waiting"
        elif ev == "admitted":
            r["phase"] = "prefill"
        elif ev == "prefill_chunk":
            r["chunks"] += 1
        elif ev == "first_token":
            r["ttft_ms"] = e.get("ttft_ms")
        elif ev == "decode":
            r["phase"] = "decode"
        elif ev == "preempt":
            r["preempts"] += 1
            r["phase"] = "waiting"
        elif ev == "requeue":
            r["requeues"] += 1
            r["phase"] = "waiting"
        elif ev == "stall":
            r["stalls"] += 1
        elif ev == "failover":
            # re-dispatched from a dead replica — queued again here
            r["phase"] = "waiting"
        elif ev == "migrate":
            # KV pages handed over mid-decode — no prefill replay
            r["phase"] = "decode"
        elif ev == "handoff":
            # ISSUE 20 disaggregation: prefilled KV landed on a
            # decode-role replica — decoding continues here
            r["phase"] = "decode"
        elif ev == "finish":
            r["phase"] = "finished"
            r["ttft_ms"] = e.get("ttft_ms", r["ttft_ms"])
            r["tpot_ms"] = e.get("tpot_ms")
            r["n_tokens"] = e.get("n_tokens")
            r["slo_ok"] = e.get("slo_ok")
        elif ev in ("error", "deadline_exceeded", "shed"):
            # ISSUE 11 terminal failure states all render as the
            # error phase; the counts dict keeps them distinguishable
            r["phase"] = "error"
    # re-judge requests whose journal predates the monitor's verdict
    # (or judge against CLI-supplied targets)
    for r in reqs.values():
        if r["slo_ok"] is None and r["phase"] == "finished" \
                and (ttft_target is not None or tpot_target is not None):
            ttft_ok = (r["ttft_ms"] is None or ttft_target is None
                       or r["ttft_ms"] <= ttft_target)
            tpot_ok = (r["tpot_ms"] is None or tpot_target is None
                       or r["tpot_ms"] <= tpot_target)
            r["slo_ok"] = ttft_ok and tpot_ok
    finished = [r for r in reqs.values() if r["phase"] == "finished"]
    judged = [r for r in finished if r["slo_ok"] is not None]
    ok = [r for r in judged if r["slo_ok"]]
    goodput = (len(ok) / len(judged)) if judged else None
    burn = None if goodput is None \
        else (1.0 - goodput) / max(1.0 - objective, 1e-9)
    phases = {"waiting": 0, "prefill": 0, "decode": 0, "finished": 0,
              "error": 0}
    for r in reqs.values():
        phases[r["phase"]] = phases.get(r["phase"], 0) + 1
    adaptered = [r for r in reqs.values() if r.get("adapter")]
    return {
        "adapters": sorted({r["adapter"] for r in adaptered}),
        "adaptered_requests": len(adaptered),
        "events": len(events),
        "requests": reqs,
        "queue_depth": phases["waiting"],
        "prefilling": phases["prefill"],
        "active": phases["decode"],
        "finished": phases["finished"],
        "judged": len(judged),
        "ok": len(ok),
        "goodput": goodput,
        "burn_rate": burn,
        "objective": objective,
        "preemptions": counts["preempt"],
        "requeues": counts["requeue"],
        "stalls": counts["stall"],
        "errors": (counts["error"] + counts["deadline_exceeded"]
                   + counts["shed"]),
        "deadline_exceeded": counts["deadline_exceeded"],
        "shed": counts["shed"],
        "retries": counts["retry"],
        "watchdog_trips": counts["watchdog"],
        "faults_injected": counts["fault"],
        "failovers": counts["failover"],
        "migrations": counts["migrate"],
        "drains": counts["drain"],
        "handoffs": counts["handoff"],
        "evicted_pages": evicted_pages,
        "spilled_pages": spilled_pages,
        "restored_pages": restored_pages,
        "spec_rounds": spec_rounds,
        "spec_drafted": spec_drafted,
        "spec_accepted": spec_accepted,
        "spec_accept_rate": (spec_accepted / spec_drafted)
        if spec_drafted else None,
        "alerts_fired": alerts_fired,
        "alerts_resolved": alerts_resolved,
        "alerts_active": sorted(alerts_active),
        "slots": None,  # live mode fills the real max_batch
    }


def _fmt(v, nd=1, unit=""):
    return "-" if v is None else f"{v:.{nd}f}{unit}"


def _timeline_lines(r: dict) -> List[str]:
    """One indented line per journal event, offset-relative to the
    request's first event (the forensic view: every admission,
    chunk, preemption and requeue of one request's life)."""
    evs = sorted(r["events"], key=lambda d: d.get("seq", 0))
    if not evs:
        return []
    t0 = float(evs[0]["ts"])
    lines = []
    for e in evs:
        extras = " ".join(
            f"{k}={v}" for k, v in e.items()
            if k not in ("seq", "ts", "ev", "rid", "slot"))
        slot = e.get("slot", -1)
        slot_s = f" slot={slot}" if isinstance(slot, int) and slot >= 0 \
            else ""
        lines.append(f"    +{(float(e['ts']) - t0) * 1e3:9.1f}ms "
                     f"{e['ev']:<13}{slot_s}"
                     + (f" {extras}" if extras else ""))
    return lines


def _request_row(r: dict) -> str:
    verdict = ("SLO ok" if r["slo_ok"] else "SLO MISS") \
        if r["slo_ok"] is not None else "unjudged"
    adapter = f"  adapter {r['adapter']}" if r.get("adapter") else ""
    return (f"  req {r['rid']:<5} {r['phase']:<9} "
            f"ttft {_fmt(r['ttft_ms'], 1, 'ms'):>9}  "
            f"tpot {_fmt(r['tpot_ms'], 2, 'ms'):>9}  "
            f"tok {r['n_tokens'] if r['n_tokens'] is not None else '-':>4}  "
            f"preempts {r['preempts']}  requeues {r['requeues']}  "
            f"{verdict}{adapter}")


def render(summary: dict, top: int = 5,
           req: Optional[int] = None) -> str:
    """Dashboard text. ``req`` narrows to one request's timeline;
    otherwise the top-k slowest finished requests (by TTFT) get
    theirs, after the one-line service header rows."""
    s = summary
    if req is not None:
        r = s["requests"].get(req)
        if r is None:
            return f"serve_top: no events for req {req}"
        return "\n".join([_request_row(r)] + _timeline_lines(r))
    slots = f"/{s['slots']}" if s.get("slots") else ""
    lines = [
        f"serve_top — {s['events']} events, "
        f"{len(s['requests'])} requests",
        f"phase: waiting {s['queue_depth']}  "
        f"prefill {s['prefilling']}  decode {s['active']}{slots}  "
        f"finished {s['finished']}  errors {s['errors']}",
        f"goodput {_fmt(s['goodput'], 3)} "
        f"({s['ok']}/{s['judged']} within SLO)   "
        f"burn_rate {_fmt(s['burn_rate'], 1, 'x')} "
        f"(objective {s['objective']})",
        f"pressure: preempts {s['preemptions']}  "
        f"requeues {s['requeues']}  stalls {s['stalls']}  "
        f"evicted_pages {s['evicted_pages']}",
        f"faults: injected {s.get('faults_injected', 0)}  "
        f"retries {s.get('retries', 0)}  "
        f"watchdog {s.get('watchdog_trips', 0)}  "
        f"deadline_exceeded {s.get('deadline_exceeded', 0)}  "
        f"shed {s.get('shed', 0)}",
    ]
    if s.get("failovers") or s.get("migrations") or s.get("drains") \
            or s.get("handoffs"):
        # fleet tier (ISSUE 14): requests that crossed replicas
        lines.append(
            f"fleet: failovers_in {s.get('failovers', 0)}  "
            f"migrations_in {s.get('migrations', 0)}  "
            f"handoffs_in {s.get('handoffs', 0)}  "
            f"drains {s.get('drains', 0)}")
    if s.get("spilled_pages") or s.get("restored_pages"):
        # tiered KV (ISSUE 20): pool pressure demoted to host DRAM
        # instead of evict-and-recompute
        lines.append(
            f"kv tier: spilled_pages {s.get('spilled_pages', 0)}  "
            f"restored_pages {s.get('restored_pages', 0)}")
    if s.get("adaptered_requests"):
        # batched multi-LoRA (ISSUE 18): how many distinct adapters
        # the journal's traffic mixed, and over how many requests
        ads = s.get("adapters") or []
        shown = ",".join(ads[:6]) + ("..." if len(ads) > 6 else "")
        lines.append(
            f"lora: {len(ads)} adapters over "
            f"{s['adaptered_requests']} requests ({shown})")
    if s.get("spec_rounds"):
        # speculative decoding (ISSUE 12): the accept-rate row — the
        # one number that says whether the drafter is paying for its
        # verify passes
        lines.append(
            f"speculative: rounds {s['spec_rounds']}  "
            f"accept_rate {_fmt(s.get('spec_accept_rate'), 3)} "
            f"({s.get('spec_accepted', 0)}/{s.get('spec_drafted', 0)} "
            "drafts accepted)")
    if s.get("alerts_fired") or s.get("alerts_resolved"):
        # continuous telemetry (ISSUE 16): alert-rule transitions
        active = s.get("alerts_active") or []
        lines.append(
            f"alerts: fired {s.get('alerts_fired', 0)}  "
            f"resolved {s.get('alerts_resolved', 0)}  "
            f"active {','.join(active) if active else '-'}")
    slowest = sorted(
        (r for r in s["requests"].values()
         if r["phase"] == "finished" and r["ttft_ms"] is not None),
        key=lambda r: -r["ttft_ms"])[:max(top, 0)]
    if slowest:
        lines.append(f"slowest {len(slowest)} finished requests "
                     "(by TTFT):")
        for r in slowest:
            lines.append(_request_row(r))
            lines.extend(_timeline_lines(r))
    unfinished = [r for r in s["requests"].values()
                  if r["phase"] not in ("finished",)]
    if unfinished:
        lines.append(f"in flight ({len(unfinished)}):")
        for r in sorted(unfinished, key=lambda r: r["rid"])[:top]:
            lines.append(_request_row(r))
    return "\n".join(lines)


def render_engine(eng, top: int = 5) -> str:
    """Live dashboard over a RUNNING ServingEngine (in-process): the
    journal's event-derived view, with the engine's real queue/slot
    state overriding the event-derived occupancy."""
    j = getattr(eng, "journal", None)
    events = j.events() if j is not None else []
    slo = getattr(eng, "slo", None)
    s = summarize(
        events,
        ttft_target=getattr(slo, "ttft_target_ms", None),
        tpot_target=getattr(slo, "tpot_target_ms", None),
        objective=getattr(slo, "goodput_objective", 0.99))
    s["queue_depth"] = len(eng.waiting) + len(getattr(eng, "_inbox", []))
    s["active"] = eng.num_active
    s["prefilling"] = getattr(eng, "num_prefilling", 0)
    s["slots"] = eng.max_batch
    mon = getattr(eng, "slo_monitor", None)
    if mon is not None and mon.goodput is not None:
        s["goodput"], s["burn_rate"] = mon.goodput, mon.burn_rate
    head = "" if j is not None else \
        "serve_top: journal disabled (FLAGS_serve_journal=0) — " \
        "live gauges only\n"
    return head + render(s, top=top)


def _fleet_row(idx, state, queue, prefill, active, finished, errors,
               goodput, failovers, migrations, extra="") -> str:
    return (f"  r{idx:<3} {state:<9} queue {queue:>3}  "
            f"prefill {prefill:>2}  decode {active:>2}  "
            f"finished {finished:>4}  errors {errors:>3}  "
            f"goodput {_fmt(goodput, 3):>6}  "
            f"failovers_in {failovers:>2}  migrations_in "
            f"{migrations:>2}{extra}")


def render_fleet_offline(paths: List[str], jm, ttft_target=None,
                         tpot_target=None, objective=0.99) -> str:
    """Fleet dashboard from per-replica journal JSONLs
    (``FleetRouter.export_journals`` / ``serve_bench --fleet
    --journal-out``): one health/occupancy/goodput row per replica
    (replica id = file order) + the merged request-level view —
    request ids are fleet-unique, so one request's failover/migration
    hops appear on every replica journal they touched."""
    all_events: List[dict] = []
    rows = [f"serve_top --fleet — {len(paths)} replica journals"]
    for i, p in enumerate(paths):
        events, _extras = jm.load_jsonl(p)
        all_events.extend(events)
        s = summarize(events, ttft_target=ttft_target,
                      tpot_target=tpot_target, objective=objective)
        rows.append(_fleet_row(
            i, "journal", s["queue_depth"], s["prefilling"],
            s["active"], s["finished"], s["errors"], s["goodput"],
            s["failovers"], s["migrations"],
            extra=f"  ({len(events)} events)"))
    merged = summarize(all_events, ttft_target=ttft_target,
                       tpot_target=tpot_target, objective=objective)
    rows.append("merged fleet view:")
    rows.append(render(merged))
    return "\n".join(rows)


def render_fleet(router, top: int = 5) -> str:
    """Live dashboard over a RUNNING FleetRouter: per-replica
    health/breaker/occupancy/goodput rows plus the fleet-tier
    failover/migration/hedge accounting from the stats registry."""
    from paddle_tpu.profiler import stats

    lines = [f"serve_top --fleet — {len(router.replicas)} replicas "
             f"(policy {router.policy})"]
    for rep in router.replicas:
        eng = rep.eng
        mon = getattr(eng, "slo_monitor", None)
        goodput = mon.goodput if mon is not None else None
        extra = ""
        if rep.breaker.state != "closed":
            extra = f"  breaker {rep.breaker.state}"
        jr = getattr(eng, "journal", None)
        n_fo = n_mig = 0
        if jr is not None:
            for e in jr.events():
                if e["ev"] == "failover":
                    n_fo += 1
                elif e["ev"] == "migrate":
                    n_mig += 1
        lines.append(_fleet_row(
            rep.idx, rep.state, eng.queue_depth, eng.num_prefilling,
            eng.num_active, len(eng.finished),
            sum(1 for r in eng.finished
                if getattr(r, "state", "ok") != "ok"),
            goodput, n_fo, n_mig, extra=extra))
    c = stats.counter
    lines.append(
        f"fleet: failovers {int(c('fleet.failovers').value)}  "
        f"failover_requests "
        f"{int(c('fleet.failover_requests').value)}  "
        f"migrations {int(c('fleet.migrations').value)} "
        f"({int(c('fleet.migrated_pages').value)} pages)  "
        f"hedges {int(c('fleet.hedges').value)}  "
        f"shed {int(c('fleet.shed').value)}  pending "
        f"{router.pending()}")
    if getattr(router, "disagg", None) is not None or any(
            getattr(r.eng, "host_tier", None) is not None
            for r in router.replicas):
        # ISSUE 20: the tiered-KV / disaggregation view — per-replica
        # role and HBM-vs-host page residency, then the directory's
        # routing outcome mix (hit = HBM holder, pull = host restore
        # beat re-prefill, miss = re-prefill anyway)
        for rep in router.replicas:
            eng, mgr = rep.eng, rep.eng._mgr
            ht = getattr(eng, "host_tier", None)
            host = f"{len(ht)} pages / {ht.bytes_used}B" \
                if ht is not None else "-"
            lines.append(
                f"  r{rep.idx:<3} role {rep.role or 'mixed':<7} "
                f"hbm {mgr.num_pages - mgr.free_pages:>4}"
                f"/{mgr.num_pages:<4} pages  host {host}")
        hits = int(c("fleet.directory_hits").value)
        pulls = int(c("fleet.directory_pulls").value)
        misses = int(c("fleet.directory_misses").value)
        probes = hits + pulls + misses
        rate = f"{hits / probes:.3f}" if probes else "-"
        lines.append(
            f"  directory: hits {hits}  pulls {pulls}  "
            f"misses {misses}  hit_rate {rate}  "
            f"handoffs {int(c('fleet.handoffs').value)} "
            f"({int(c('fleet.handoff_pages').value)} pages)  "
            f"spills {int(c('fleet.spills').value)}  restores "
            f"{int(c('fleet.restores').value)}")
    if getattr(router, "usage", None) is not None or any(
            getattr(r.eng, "usage", None) is not None
            for r in router.replicas):
        # ISSUE 17: the merged fleet tenant view — per-replica
        # ledgers folded so a failed-over request is charged once
        from paddle_tpu.serving import accounting as am

        lines.append(render_tenants(router.fleet_usage(), am,
                                    top=top))
    return "\n".join(lines)


# ---------------- per-tenant usage (ISSUE 17) ----------------


def render_tenants(records: List[dict], am, top: int = 10) -> str:
    """Per-tenant usage table over (possibly folded) usage records:
    attributed device time + share of it, KV page-seconds, queue
    seconds, token counts, the wasted-token share (the chunk-tail
    tokens a finishing request stranded), and the terminal-state mix.
    ``am`` is the accounting module (standalone or package form)."""
    roll = am.tenant_rollup(records)
    if not roll:
        return "serve_top --tenants: no usage records"
    rows = sorted(roll.values(),
                  key=lambda a: (-a["device_ns"], a["tenant"]))
    lines = [
        f"serve_top --tenants — {len(rows)} tenants, "
        f"{sum(a['n_requests'] for a in rows)} requests, "
        f"{sum(a['device_ms'] for a in rows):.1f}ms attributed "
        "device time",
        f"  {'tenant':<14} {'reqs':>5} {'device_ms':>10} "
        f"{'share':>6} {'kv_page_s':>10} {'queue_s':>8} "
        f"{'prefill':>8} {'decode':>7} {'waste':>6} {'lora':>5} "
        "states",
    ]
    for a in rows[:max(top, 0)]:
        states = ",".join(f"{k}:{v}" for k, v in
                          sorted(a["states"].items()))
        # distinct LoRA adapters this tenant's requests rode (ISSUE
        # 18); "-" for pure-base traffic
        n_ad = len(a.get("adapters") or ())
        lines.append(
            f"  {a['tenant']:<14} {a['n_requests']:>5} "
            f"{a['device_ms']:>10.3f} {a['share']:>6.1%} "
            f"{a['kv_page_s']:>10.4f} {a['queue_s']:>8.4f} "
            f"{a['prefill_tokens']:>8} {a['decode_tokens']:>7} "
            f"{a['waste_share']:>6.1%} {n_ad if n_ad else '-':>5} "
            f"{states}")
    if len(rows) > top > 0:
        lines.append(f"  ... {len(rows) - top} more tenants")
    return "\n".join(lines)


def render_tenants_engine(eng, top: int = 10) -> str:
    """Live per-tenant table over a RUNNING ServingEngine's usage
    ledger (open records included — in-flight requests show their
    running charges)."""
    u = getattr(eng, "usage", None)
    if u is None:
        return ("serve_top --tenants: usage ledger disabled "
                "(FLAGS_usage_ledger=0)")
    from paddle_tpu.serving import accounting as am

    return render_tenants(u.records(include_open=True), am, top=top)


# ---------------- telemetry history (ISSUE 16) ----------------

_SPARKS = "▁▂▃▄▅▆▇█"


def sparkline(values: List[Optional[float]], lo=None, hi=None) -> str:
    """Unicode sparkline; None values render as gaps. ``lo``/``hi``
    pin the scale (goodput wants 0..1); default is the window's
    min/max."""
    present = [v for v in values if v is not None]
    if not present:
        return " " * len(values)
    lo = min(present) if lo is None else lo
    hi = max(present) if hi is None else hi
    span = hi - lo
    out = []
    for v in values:
        if v is None:
            out.append(" ")
            continue
        x = 0.5 if span <= 0 else (v - lo) / span
        out.append(_SPARKS[min(int(x * len(_SPARKS)),
                               len(_SPARKS) - 1)])
    return "".join(out)


def _gauge_series(ticks, name):
    return [t.get("gauges", {}).get(name) for t in ticks]


def _rate_series(ticks, name):
    out = []
    for t in ticks:
        pair = t.get("counters", {}).get(name)
        out.append(pair[1] if pair else None)
    return out


def _hist_totals(t, prefix="serve.step.", names=None):
    h = t.get("histograms", {})
    tot = 0.0
    for n, pair in h.items():
        if names is not None:
            if n in names:
                tot += pair[1]
        elif n.startswith(prefix):
            tot += pair[1]
    return tot


_WORK_PHASES = ("serve.step.prefill_chunk_ms",
                "serve.step.decode_chunk_ms",
                "serve.step.spec_verify_ms",
                "serve.step.migration_ms")


def _occupancy_series(ticks):
    """Per-tick work fraction: delta of the work-phase histogram
    totals over the delta of ``serve.step.total_ms`` — how much of
    each interval's step time was accelerator-facing work vs admit +
    host overhead."""
    out: List[Optional[float]] = []
    prev_w = prev_t = None
    for t in ticks:
        w = _hist_totals(t, names=set(_WORK_PHASES))
        tot = _hist_totals(t, names={"serve.step.total_ms"})
        if prev_t is None or tot <= prev_t:
            out.append(None)
        else:
            out.append(max(0.0, min(1.0, (w - prev_w)
                                    / (tot - prev_t))))
        prev_w, prev_t = w, tot
    return out


def render_history(ticks: List[dict], width: int = 60) -> str:
    """Sparkline dashboard over a telemetry tick series (a live
    ``TimeSeriesSampler.ticks()`` or a ``--telemetry-out`` JSONL
    dump): goodput / burn / queue depth / throughput / phase
    occupancy over the window, with an alert-marker row (``!`` =
    tick with active alerts)."""
    if not ticks:
        return "serve_top --history: no telemetry ticks"
    ticks = ticks[-width:]
    span_s = ticks[-1].get("ts", 0.0) - ticks[0].get("ts", 0.0)
    lines = [f"serve_top --history — {len(ticks)} ticks "
             f"({span_s:.1f}s window)"]

    def row(label, values, lo=None, hi=None, fmt="{:.2f}"):
        present = [v for v in values if v is not None]
        last = fmt.format(present[-1]) if present else "-"
        lines.append(f"  {label:<12} {sparkline(values, lo, hi)}"
                     f"  last {last}")

    goodput = _gauge_series(ticks, "slo.goodput")
    if any(v is not None for v in goodput):
        row("goodput", goodput, lo=0.0, hi=1.0, fmt="{:.3f}")
    burn = _gauge_series(ticks, "slo.burn_rate")
    if any(v is not None for v in burn):
        row("burn_rate", burn, lo=0.0, fmt="{:.1f}x")
    queue = _gauge_series(ticks, "slo.queue_depth")
    if any(v is not None for v in queue):
        row("queue", queue, lo=0.0, fmt="{:.0f}")
    # throughput: the first counter that produced rates, preferring
    # token/step counters over bookkeeping ones
    for cname in ("serving.decode_tokens", "serving.decode_steps",
                  "serve.finished", "serving.finished"):
        rates = _rate_series(ticks, cname)
        if any(v is not None for v in rates):
            row(f"{cname.rsplit('.', 1)[-1]}/s", rates, lo=0.0,
                fmt="{:.1f}")
            break
    occ = _occupancy_series(ticks)
    if any(v is not None for v in occ):
        row("work_frac", occ, lo=0.0, hi=1.0)
    marks = "".join("!" if t.get("alerts") else "." for t in ticks)
    if "!" in marks:
        lines.append(f"  {'alerts':<12} {marks}")
        firing: List[str] = []
        for t in ticks:
            for a in t.get("alerts", []):
                if a not in firing:
                    firing.append(a)
        lines.append(f"  fired in window: {', '.join(firing)}")
    return "\n".join(lines)


def _watch_loop(render_once, interval_s: float, clk=None,
                max_iters: Optional[int] = None,
                out=None) -> int:
    """The ``--watch``/``--interval`` loop: clear-then-redraw at a
    fixed cadence, SLEEPING THROUGH THE CLOCK SEAM (``clk.sleep``) so
    tests drive it with a ManualClock and ``max_iters`` instead of
    wall time. ``interval_s <= 0`` renders once."""
    out = out if out is not None else sys.stdout
    if clk is None:
        clk = _faults_mod().clock()
    i = 0
    while True:
        body = render_once()
        if interval_s > 0:
            # clear first, THEN draw: the frame lands on a blank
            # screen in one piece (stable columns, no torn redraw)
            out.write("\033[2J\033[H")
        out.write(body + "\n")
        try:
            out.flush()
        except Exception:
            pass
        i += 1
        if interval_s <= 0 or (max_iters is not None
                               and i >= max_iters):
            return 0
        clk.sleep(interval_s)


def _crash_lines(extras: dict) -> List[str]:
    crash = extras.get("crash")
    if not crash:
        return []
    unserved = crash.get("unserved") or []
    lines = [f"crash: {crash.get('error')}   "
             f"in-flight at dump: {len(unserved)}   "
             f"dropped_events: {crash.get('dropped_events', 0)}"]
    for u in unserved:
        where = u.get("state", "?")
        extra = " ".join(f"{k}={v}" for k, v in u.items()
                         if k not in ("rid", "state"))
        lines.append(f"  req {u.get('rid'):<5} {where:<11} {extra}")
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="text dashboard over a serving journal / crash "
                    "dump (serving/journal.py JSONL)")
    ap.add_argument("journal", nargs="*",
                    help="journal or crash-dump JSONL path; with "
                         "--fleet, one per replica (replica id = "
                         "argument order); with --tenants, usage "
                         "JSONL dump(s); optional with --history")
    ap.add_argument("--tenants", action="store_true",
                    help="per-tenant usage table (ISSUE 17) from "
                         "usage JSONL dump(s) (serve_bench "
                         "--usage-out / FleetRouter.export_usage); "
                         "several dumps fold to one record per "
                         "request first — the merged fleet tenant "
                         "view")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet view (ISSUE 14): one health/"
                         "occupancy/goodput row per replica journal "
                         "+ the merged request-level dashboard "
                         "(failover/migration hops fold by request "
                         "id)")
    ap.add_argument("--top", type=int, default=5,
                    help="slowest-request timelines to render")
    ap.add_argument("--req", type=int, default=None,
                    help="render ONE request's full timeline")
    ap.add_argument("--ttft-target", type=float, default=None,
                    help="re-judge verdicts offline: TTFT target (ms)")
    ap.add_argument("--tpot-target", type=float, default=None,
                    help="re-judge verdicts offline: TPOT target (ms)")
    ap.add_argument("--objective", type=float, default=0.99,
                    help="goodput objective for the burn rate")
    ap.add_argument("--export-trace", default=None,
                    help="also write the one-lane-per-request chrome "
                         "trace here (trace_merge-foldable)")
    ap.add_argument("--rank", type=int, default=None,
                    help="process_index stamp for --export-trace "
                         "(default: the journal's stats stamp, else 0)")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="re-read + re-render every N seconds "
                         "(0 = render once)")
    ap.add_argument("--interval", type=float, default=None,
                    help="watch cadence in seconds, routed through "
                         "the serving clock seam (ISSUE 16; implies "
                         "--watch; ManualClock-testable)")
    ap.add_argument("--history", default=None, metavar="SERIES.jsonl",
                    help="sparkline dashboard over a telemetry "
                         "time-series dump (TimeSeriesSampler."
                         "dump_jsonl / serve_bench --telemetry-out)")
    ap.add_argument("--width", type=int, default=60,
                    help="--history sparkline width (ticks shown)")
    args = ap.parse_args(argv)

    interval = args.interval if args.interval is not None \
        else args.watch
    jm = _journal_mod()

    if args.tenants:
        if not args.journal:
            ap.error("--tenants needs usage JSONL path(s)")
        am = _accounting_mod()

        def render_once():
            recs: List[dict] = []
            for p in args.journal:
                recs.extend(am.load_usage_jsonl(p))
            return render_tenants(am.fold_records(recs), am,
                                  top=max(args.top, 10))
        return _watch_loop(render_once, interval)

    if args.history is None and not args.journal:
        ap.error("pass a journal JSONL (or --history SERIES.jsonl)")

    if args.history is not None and not args.journal:
        tsm = _ts_mod()

        def render_once():
            return render_history(tsm.load_jsonl(args.history),
                                  width=args.width)
        return _watch_loop(render_once, interval)

    if args.fleet or len(args.journal) > 1:
        def render_once():
            return render_fleet_offline(
                args.journal, jm, ttft_target=args.ttft_target,
                tpot_target=args.tpot_target,
                objective=args.objective)
        return _watch_loop(render_once, interval)

    def render_once():
        events, extras = jm.load_jsonl(args.journal[0])
        summary = summarize(events, ttft_target=args.ttft_target,
                            tpot_target=args.tpot_target,
                            objective=args.objective)
        out = render(summary, top=args.top, req=args.req)
        crash = _crash_lines(extras)
        if crash:
            out = out + "\n" + "\n".join(crash)
        if args.history:
            tsm = _ts_mod()
            out += "\n" + render_history(
                tsm.load_jsonl(args.history), width=args.width)
        if args.export_trace:
            rank = args.rank
            if rank is None:
                rank = ((extras.get("stats") or {}).get("stats") or {}) \
                    .get("meta", {}).get("process_index", 0)
            with open(args.export_trace, "w") as f:
                json.dump(jm.chrome_trace(events, process_index=rank),
                          f)
            out += f"\nserve_top: chrome trace -> {args.export_trace}"
            args.export_trace = None  # once per invocation
        return out

    return _watch_loop(render_once, interval)


if __name__ == "__main__":
    sys.exit(main())
