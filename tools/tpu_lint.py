#!/usr/bin/env python
"""tpu_lint — static analysis for the repo's TPU kernels and compiled
programs, runnable entirely on CPU.

Seven ``paddle_tpu.analysis`` passes (plus the flags/README parity
check) report findings:

  kernel-level (PR 6)
  geometry   dry-traces every pallas_call site through the audit shim
             and validates VMEM footprint vs the declared limit and the
             per-generation budget (device/vmem.py), tile alignment,
             grid divisibility, index-map bounds, magic VMEM literals
  donation   static audit of the op registry's buffer-donation
             contracts (the runtime poison mode is FLAGS_check_donation)
  purity     AST lint of traced code for concretization hazards
  flags      FLAGS_* / PADDLE_TPU_* / README conventions parity

  program-level (PR 7): whole-jaxpr passes over the registered program
  sites (jit'd composites, train step, serving prefill/decode)
  dtype      silent bf16->f32 matmul promotion (X-PROMOTE), f64 leaks
             (X-F64)
  sync       host callbacks in hot loops (X-SYNC), recompile-churn
             statics (X-CHURN)
  memory     donation-aware liveness walk -> static HBM-peak bound per
             program vs the per-generation capacity table (M-HBM)
  spmd       distributed surfaces compiled on a virtual 8-device CPU
             mesh: undeclared collectives (S-GATHER), asymmetric
             branch collectives (S-MATCH), unconstrained outputs
             (S-UNSPEC)
  overlap    comm/compute overlap sites keep their exact collective
             census — ring phase counts / permute ordering, the
             double-buffered EP exchange, no stray blocking psum
             (S-OVERLAP)

Exit status is nonzero when any UNWAIVERED finding exists. Intentional
exceptions are documented in-line::

    risky()  # tpu-lint: ok(P-HOST-RNG) -- reseeded per trace

Usage:
    python tools/tpu_lint.py [--json] [--pass NAME] [--generation GEN]
                             [--baseline FILE] [--write-baseline FILE]

    --json           machine-readable report on stdout (for CI); the
                     schema carries `schema_version` and every WAIVED
                     finding with its reason (audit trail)
    --pass NAME      run one pass (default: all)
    --generation GEN validate VMEM/HBM against a TPU generation
                     (v2|v3|v4|v5e|v5p|v6e; default: attached chip,
                     else the v5e serving target)
    --baseline FILE  ratchet mode: compare per-rule unwaivered counts
                     against a previous --json report (or a
                     --write-baseline file); exit nonzero only when a
                     rule's count GREW — CI enforces "no new findings"
                     without blocking on legacy ones
    --write-baseline FILE  write the current per-rule counts for later
                     --baseline runs (implies exit 0)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

#: --json schema: 1 = PR 6 (four passes); 2 = PR 7 (seven passes +
#: schema_version + waived_findings + rule_counts)
SCHEMA_VERSION = 2


def _ensure_virtual_mesh():
    """The SPMD pass needs 8 virtual CPU devices, which XLA only grants
    at backend init — set the flag before jax is imported (no-op when
    jax is already up, e.g. embedded callers; the pass then skips)."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _baseline_counts(doc: dict) -> dict:
    """Per-rule unwaivered counts from a baseline file: either a full
    --json report (counts recomputed from its findings) or a
    --write-baseline {"rule_counts": ...} stub."""
    if "rule_counts" in doc:
        return {str(k): int(v) for k, v in doc["rule_counts"].items()}
    counts: dict = {}
    for fs in doc.get("passes", {}).values():
        for f in fs:
            if not f.get("waived"):
                counts[f["rule"]] = counts.get(f["rule"], 0) + 1
    return counts


def main(argv=None) -> int:
    _ensure_virtual_mesh()
    from paddle_tpu import analysis

    ap = argparse.ArgumentParser(
        prog="tpu_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit a machine-readable JSON report")
    ap.add_argument("--pass", dest="which", choices=analysis.PASS_NAMES,
                    help="run a single pass (default: all)")
    ap.add_argument("--generation", default=None,
                    help="TPU generation for the VMEM/HBM budget checks")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="ratchet: fail only on rules whose unwaivered "
                         "count grew vs this report")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="write current per-rule counts for --baseline")
    args = ap.parse_args(argv)

    t0 = time.time()
    runners = {
        "geometry": lambda: analysis.run_geometry_pass(
            generation=args.generation),
        "donation": analysis.run_donation_pass,
        "purity": analysis.run_purity_pass,
        "flags": analysis.run_flags_pass,
        "dtype": analysis.run_dtype_pass,
        "sync": analysis.run_sync_pass,
        "memory": lambda: analysis.run_memory_pass(
            generation=args.generation),
        "spmd": analysis.run_spmd_pass,
        "overlap": analysis.run_overlap_pass,
    }
    if args.which:
        results = {args.which: runners[args.which]()}
    else:
        results = analysis.run_all_passes(generation=args.generation)
    elapsed = time.time() - t0

    from paddle_tpu.analysis.preflight import publish_lint_stats

    publish_lint_stats(results)

    n_unwaivered = sum(len(analysis.unwaivered(fs))
                       for fs in results.values())
    n_waived = sum(sum(1 for f in fs if f.waived)
                   for fs in results.values())
    counts = analysis.rule_counts(results)

    ratchet_bad = None
    if args.baseline:
        with open(args.baseline, encoding="utf-8") as f:
            ratchet_bad = analysis.ratchet(counts,
                                           _baseline_counts(json.load(f)))
    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump({"schema_version": SCHEMA_VERSION,
                       "rule_counts": counts}, f, indent=2)
            f.write("\n")

    if args.as_json:
        json.dump({
            "schema_version": SCHEMA_VERSION,
            "passes": {k: [f.to_dict() for f in fs]
                       for k, fs in results.items()},
            # audit trail: every waived finding with its reason, flat
            "waived_findings": [f.to_dict()
                                for fs in results.values()
                                for f in fs if f.waived],
            "rule_counts": counts,
            "unwaivered": n_unwaivered,
            "waived": n_waived,
            "elapsed_s": round(elapsed, 2),
            "ok": (not ratchet_bad if ratchet_bad is not None
                   else n_unwaivered == 0),
            "ratchet": ratchet_bad,
        }, sys.stdout, indent=2)
        print()
    else:
        for name, fs in results.items():
            live = analysis.unwaivered(fs)
            status = "clean" if not live else f"{len(live)} finding(s)"
            print(f"[{name}] {status}"
                  + (f" (+{len(fs) - len(live)} waived)"
                     if len(fs) != len(live) else ""))
            for f in fs:
                print("   " + f.render())
        print(f"tpu_lint: {n_unwaivered} unwaivered finding(s), "
              f"{n_waived} waived, {elapsed:.1f}s")
        if ratchet_bad is not None:
            if ratchet_bad:
                print("ratchet REGRESSIONS vs baseline:")
                for line in ratchet_bad:
                    print("  " + line)
            else:
                print("ratchet: no new findings vs baseline")

    if ratchet_bad is not None:
        return 1 if ratchet_bad else 0
    return 1 if n_unwaivered else 0


if __name__ == "__main__":
    sys.exit(main())
