#!/usr/bin/env python
"""tpu_lint — static analysis for the repo's TPU kernels and traced
code, runnable entirely on CPU.

Runs the three ``paddle_tpu.analysis`` passes (plus the flags/README
parity check) and reports findings:

  geometry   dry-traces every pallas_call site through the audit shim
             and validates VMEM footprint vs the declared limit and the
             per-generation budget (device/vmem.py), tile alignment,
             grid divisibility, index-map bounds, magic VMEM literals
  donation   static audit of the op registry's buffer-donation
             contracts (the runtime poison mode is FLAGS_check_donation)
  purity     AST lint of traced code for concretization hazards
  flags      FLAGS_* / PADDLE_TPU_* / README conventions parity

Exit status is nonzero when any UNWAIVERED finding exists. Intentional
exceptions are documented in-line::

    risky()  # tpu-lint: ok(P-HOST-RNG) -- reseeded per trace

Usage:
    python tools/tpu_lint.py [--json] [--pass NAME] [--generation GEN]

    --json           machine-readable report on stdout (for CI)
    --pass NAME      run one pass: geometry|donation|purity|flags
    --generation GEN validate VMEM against a specific TPU generation
                     (v2|v3|v4|v5e|v5p|v6e; default: attached chip,
                     else the v5e serving target)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

PASSES = ("geometry", "donation", "purity", "flags")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit a machine-readable JSON report")
    ap.add_argument("--pass", dest="which", choices=PASSES,
                    help="run a single pass (default: all)")
    ap.add_argument("--generation", default=None,
                    help="TPU generation for the VMEM budget check")
    args = ap.parse_args(argv)

    t0 = time.time()
    from paddle_tpu import analysis

    if args.which == "geometry":
        results = {"geometry":
                   analysis.run_geometry_pass(generation=args.generation)}
    elif args.which == "donation":
        results = {"donation": analysis.run_donation_pass()}
    elif args.which == "purity":
        results = {"purity": analysis.run_purity_pass()}
    elif args.which == "flags":
        results = {"flags": analysis.run_flags_pass()}
    else:
        results = analysis.run_all_passes(generation=args.generation)
    elapsed = time.time() - t0

    n_unwaivered = sum(len(analysis.unwaivered(fs))
                       for fs in results.values())
    n_waived = sum(sum(1 for f in fs if f.waived)
                   for fs in results.values())

    if args.as_json:
        json.dump({
            "passes": {k: [f.to_dict() for f in fs]
                       for k, fs in results.items()},
            "unwaivered": n_unwaivered,
            "waived": n_waived,
            "elapsed_s": round(elapsed, 2),
            "ok": n_unwaivered == 0,
        }, sys.stdout, indent=2)
        print()
    else:
        for name, fs in results.items():
            live = analysis.unwaivered(fs)
            status = "clean" if not live else f"{len(live)} finding(s)"
            print(f"[{name}] {status}"
                  + (f" (+{len(fs) - len(live)} waived)"
                     if len(fs) != len(live) else ""))
            for f in fs:
                print("   " + f.render())
        print(f"tpu_lint: {n_unwaivered} unwaivered finding(s), "
              f"{n_waived} waived, {elapsed:.1f}s")
    return 1 if n_unwaivered else 0


if __name__ == "__main__":
    sys.exit(main())
