"""Fleet observability: merge per-rank chrome traces + stats snapshots.

Each rank of a multiproc run dumps its own artifacts into a shared run
dir via ``paddle_tpu.profiler.dump_rank(run_dir, profiler)`` —
``trace_rank{i}.json`` and ``stats_rank{i}.json`` (plus any
``*.paddle_trace.json`` written by ``export_chrome_tracing``). This tool
folds them into ONE fleet view:

- **merged trace**: every rank's events on one timeline with
  ``pid = rank`` (chrome://tracing / Perfetto then shows one process
  row per rank, named "rank N"), instead of N files whose pid-only
  worker names collide across hosts;
- **fleet stats snapshot**: counters summed, gauges maxed, histograms
  folded bucket-by-bucket (count/total summed, min/max widened,
  percentiles re-estimated from the folded power-of-2 buckets);
- **fleet telemetry series** (ISSUE 16): per-rank/per-replica
  time-series JSONL dumps (``TimeSeriesSampler.dump_jsonl`` /
  ``serve_bench --telemetry-out``, named ``telemetry_rank{i}.jsonl``
  or ``*.telemetry.jsonl``) fold tick-by-tick with the same
  semantics — ticks align by timestamp order, counters sum
  (cumulative + rate), gauges max, histogram count/total pairs sum,
  alert sets union — into ``merged_telemetry.jsonl``, which
  ``serve_top --history`` renders directly;
- **fleet usage ledger** (ISSUE 17): per-replica usage JSONL dumps
  (``FleetRouter.export_usage`` / ``serve_bench --usage-out``, named
  ``*usage*_r{i}.jsonl`` / ``*usage*_router.jsonl`` /
  ``usage_rank{i}.jsonl``) fold via
  ``serving.accounting.fold_records`` — dedup on (hop, rid), then
  sum per (tenant, rid) so a failed-over/migrated request is charged
  exactly once — into ``merged_usage.jsonl``, which ``serve_top
  --tenants`` renders directly.

Usage::

    python tools/trace_merge.py RUN_DIR \
        [--out-trace merged_trace.json] [--out-stats fleet_stats.json] \
        [--out-series merged_telemetry.jsonl] \
        [--out-usage merged_usage.jsonl]

Prints one JSON line {ranks, events, out_trace, out_stats,
out_series, ticks, out_usage, usage_records}.
"""
from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import re
import sys
from typing import List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

__all__ = ["merge_traces", "fold_stats", "fold_series",
           "fold_usage", "find_rank_files", "find_series_files",
           "find_usage_files", "main"]


def _ts_mod():
    """profiler/timeseries.py loaded standalone (stdlib-only at
    import) — the series fold reuses the writer's own
    load_jsonl/aggregate_ticks instead of re-implementing the
    semantics here."""
    spec = importlib.util.spec_from_file_location(
        "_tm_timeseries", os.path.join(
            _REPO, "paddle_tpu", "profiler", "timeseries.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _accounting_mod():
    """serving/accounting.py loaded standalone (stdlib-only at
    import) — the usage fold reuses the ledger's own
    load_usage_jsonl/fold_records instead of re-implementing the
    exactly-once semantics here."""
    spec = importlib.util.spec_from_file_location(
        "_tm_accounting", os.path.join(
            _REPO, "paddle_tpu", "serving", "accounting.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _rank_of(trace: dict, path: str, fallback: int) -> int:
    """Producing rank: trace metadata stamp first (authoritative),
    filename ``rank<N>`` second, enumeration order last."""
    meta = trace.get("metadata") or {}
    if isinstance(meta.get("process_index"), int):
        return meta["process_index"]
    m = re.search(r"rank(\d+)", os.path.basename(path))
    if m:
        return int(m.group(1))
    return fallback


def merge_traces(paths: List[str]) -> dict:
    """One chrome trace with each input's events re-pid'd to its rank
    and a process_name metadata row per rank."""
    events = []
    ranks = []
    for i, path in enumerate(sorted(paths)):
        with open(path) as f:
            trace = json.load(f)
        rank = _rank_of(trace, path, i)
        ranks.append(rank)
        src_pid = (trace.get("metadata") or {}).get("pid")
        events.append({
            "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
            "args": {"name": f"rank {rank}"
                             + (f" (host pid {src_pid})" if src_pid
                                else "")},
        })
        events.append({
            "name": "process_sort_index", "ph": "M", "pid": rank,
            "tid": 0, "args": {"sort_index": rank},
        })
        for e in trace.get("traceEvents", []):
            e = dict(e)
            e["pid"] = rank
            events.append(e)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"merged_from": [os.path.basename(p)
                                     for p in sorted(paths)],
                     "ranks": sorted(ranks)},
    }


def _fold_hist(summaries: List[dict]) -> dict:
    """Fold per-rank histogram summaries: counts/totals add, min/max
    widen, buckets add edge-wise, percentiles re-estimated from the
    folded buckets (same estimator as stats.Histogram.percentile)."""
    count = sum(s.get("count", 0) for s in summaries)
    total = sum(s.get("total", 0.0) for s in summaries)
    mins = [s["min"] for s in summaries if s.get("min") is not None]
    maxes = [s["max"] for s in summaries if s.get("max") is not None]
    buckets: dict = {}
    for s in summaries:
        for edge, n in s.get("buckets", []):
            buckets[float(edge)] = buckets.get(float(edge), 0) + n
    folded = sorted(buckets.items())
    mn = min(mins) if mins else None
    mx = max(maxes) if maxes else None

    def pct(q):
        if not count or not folded:
            return None
        target = q * count
        cum = 0
        for edge, n in folded:
            prev, cum = cum, cum + n
            if cum >= target:
                lo = edge / 2.0 if edge > 1.0 else 0.0
                est = lo + (edge - lo) * (target - prev) / n
                lo_c = mn if mn is not None else est
                hi_c = mx if mx is not None else est
                return round(min(max(est, lo_c), hi_c), 3)
        return mx

    return {
        "count": count,
        "total": round(total, 3),
        "avg": round(total / count, 3) if count else 0.0,
        "min": mn,
        "max": mx,
        "p50": pct(0.50),
        "p90": pct(0.90),
        "p99": pct(0.99),
        "buckets": [[e, n] for e, n in folded],
    }


def fold_stats(snapshots: List[dict]) -> dict:
    """Fold per-rank ``stats.snapshot()`` dicts into one fleet view:
    counters are event totals (sum), gauges are instantaneous levels
    (max — the fleet's high-water value), histograms fold by bucket."""
    counters: dict = {}
    gauges: dict = {}
    hists: dict = {}
    ranks = []
    for snap in snapshots:
        meta = snap.get("meta") or {}
        if "process_index" in meta:
            ranks.append(meta["process_index"])
        for k, v in snap.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, v in snap.get("gauges", {}).items():
            gauges[k] = max(gauges.get(k, float("-inf")), v)
        for k, v in snap.get("histograms", {}).items():
            hists.setdefault(k, []).append(v)
    return {
        "meta": {"ranks": sorted(ranks), "num_snapshots": len(snapshots),
                 "fold": {"counters": "sum", "gauges": "max",
                          "histograms": "bucket-fold"}},
        "counters": dict(sorted(counters.items())),
        "gauges": dict(sorted(gauges.items())),
        "histograms": {k: _fold_hist(v)
                       for k, v in sorted(hists.items())},
    }


def find_rank_files(run_dir: str) -> Tuple[List[str], List[str]]:
    """(trace_paths, stats_paths) inside a shared run dir: the
    ``dump_rank`` layout plus any ``export_chrome_tracing`` outputs."""
    traces = sorted(
        set(glob.glob(os.path.join(run_dir, "trace_rank*.json")))
        | set(glob.glob(os.path.join(run_dir, "*.paddle_trace.json"))))
    stats = sorted(glob.glob(os.path.join(run_dir, "stats_rank*.json")))
    return traces, stats


def find_series_files(run_dir: str) -> List[str]:
    """Per-rank/per-replica telemetry time-series dumps in a run dir
    (``telemetry_rank{i}.jsonl`` / ``*.telemetry.jsonl`` / the
    serve_bench ``--telemetry-out`` chaos suffix)."""
    return sorted(
        set(glob.glob(os.path.join(run_dir, "telemetry_rank*.jsonl")))
        | set(glob.glob(os.path.join(run_dir, "*.telemetry.jsonl")))
        | set(glob.glob(os.path.join(run_dir,
                                     "telemetry_r*.jsonl"))))


def fold_series(paths: List[str], tsm=None) -> List[dict]:
    """Fold per-rank telemetry series into one fleet series via the
    writer's own ``aggregate_ticks`` (counters sum, gauges max,
    histogram pairs sum, ticks aligned by timestamp order)."""
    tsm = tsm if tsm is not None else _ts_mod()
    return tsm.aggregate_ticks([tsm.load_jsonl(p) for p in paths])


def find_usage_files(run_dir: str) -> List[str]:
    """Per-replica usage-ledger dumps in a run dir (the
    ``FleetRouter.export_usage`` / ``serve_bench --usage-out`` naming:
    ``<prefix>_r{i}.jsonl`` + ``<prefix>_router.jsonl`` with "usage"
    in the prefix, or ``usage_rank{i}.jsonl``). The merged output
    itself is excluded so a re-run never folds its own product."""
    found = (
        set(glob.glob(os.path.join(run_dir, "*usage*_r*.jsonl")))
        | set(glob.glob(os.path.join(run_dir, "*usage*_router.jsonl")))
        | set(glob.glob(os.path.join(run_dir, "usage_rank*.jsonl"))))
    return sorted(p for p in found
                  if os.path.basename(p) != "merged_usage.jsonl")


def fold_usage(paths: List[str], am=None) -> List[dict]:
    """Fold per-replica usage dumps into one record per request via
    the ledger's own ``fold_records`` (dedup on (hop, rid), integer
    phase_ns/token counts sum per (tenant, rid), terminal state by
    precedence — a failed-over request is charged exactly once)."""
    am = am if am is not None else _accounting_mod()
    recs: List[dict] = []
    for p in paths:
        recs.extend(am.load_usage_jsonl(p))
    return am.fold_records(recs)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-rank chrome traces + stats snapshots "
                    "into one fleet timeline / snapshot")
    ap.add_argument("run_dir", help="shared dir the ranks dumped into")
    ap.add_argument("--out-trace", default=None,
                    help="merged trace path "
                         "(default RUN_DIR/merged_trace.json)")
    ap.add_argument("--out-stats", default=None,
                    help="fleet snapshot path "
                         "(default RUN_DIR/fleet_stats.json)")
    ap.add_argument("--out-series", default=None,
                    help="fleet telemetry series path (default "
                         "RUN_DIR/merged_telemetry.jsonl)")
    ap.add_argument("--out-usage", default=None,
                    help="folded fleet usage-ledger path (default "
                         "RUN_DIR/merged_usage.jsonl; serve_top "
                         "--tenants input)")
    args = ap.parse_args(argv)

    traces, stats = find_rank_files(args.run_dir)
    series = find_series_files(args.run_dir)
    usage = find_usage_files(args.run_dir)
    if not traces and not stats and not series and not usage:
        print(f"trace_merge: no rank files under {args.run_dir} "
              "(expected trace_rank*.json / stats_rank*.json / "
              "*.paddle_trace.json / telemetry_rank*.jsonl / "
              "*usage*_r*.jsonl)",
              file=sys.stderr)
        return 2

    out = {"ranks": 0, "events": 0,
           "out_trace": None, "out_stats": None,
           "out_series": None, "ticks": 0,
           "out_usage": None, "usage_records": 0}
    if traces:
        merged = merge_traces(traces)
        out_trace = args.out_trace or os.path.join(
            args.run_dir, "merged_trace.json")
        with open(out_trace, "w") as f:
            json.dump(merged, f)
        out["out_trace"] = out_trace
        out["events"] = len(merged["traceEvents"])
        out["ranks"] = len(merged["metadata"]["ranks"])
    if stats:
        snapshots = []
        for p in stats:
            with open(p) as f:
                snapshots.append(json.load(f))
        fleet = fold_stats(snapshots)
        out_stats = args.out_stats or os.path.join(
            args.run_dir, "fleet_stats.json")
        with open(out_stats, "w") as f:
            json.dump(fleet, f, indent=1)
        out["out_stats"] = out_stats
        out["ranks"] = max(out["ranks"], len(snapshots))
    if series:
        folded = fold_series(series)
        out_series = args.out_series or os.path.join(
            args.run_dir, "merged_telemetry.jsonl")
        with open(out_series, "w") as f:
            for rec in folded:
                f.write(json.dumps(rec) + "\n")
        out["out_series"] = out_series
        out["ticks"] = len(folded)
        out["ranks"] = max(out["ranks"], len(series))
    if usage:
        folded_u = fold_usage(usage)
        out_usage = args.out_usage or os.path.join(
            args.run_dir, "merged_usage.jsonl")
        with open(out_usage, "w") as f:
            for rec in folded_u:
                f.write(json.dumps(rec) + "\n")
        out["out_usage"] = out_usage
        out["usage_records"] = len(folded_u)
        out["ranks"] = max(out["ranks"], len(usage))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
