"""Training-step ablation for the flagship GPT rung (VERDICT r4 #6:
is a fused LN+residual kernel needed, or does XLA already fuse the
bf16 elementwise/LN chains?).

Each mode runs bench.py's gpt3-1.3b config with ONE component altered
and prints {mode, tokens_per_sec, mfu}. If `noln` (LayerNorms replaced
by identity) moves MFU by ~nothing, the LN chains are already fused
into neighbors by XLA and a hand-written kernel has no headroom.

    python tools/train_profile.py --mode full|noln|nogelu|nosdpa
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402


def run(mode):
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    if mode == "noln":
        # identity LayerNorm: same params (grads still flow via 0*),
        # no normalization math — isolates the LN chains' cost
        def fwd(self, x):
            return x + 0.0 * (self.weight + self.bias).astype(x.dtype)
        nn.LayerNorm.forward = fwd
    elif mode == "nogelu":
        import paddle_tpu.nn.functional as F

        F.gelu = lambda x, approximate=False: x
    name, d, L, h, s, b, ok = bench.LADDER[0]
    tps, n_params, fpt, roofline = bench.run_config(
        name, d, L, h, s, b, steps=10, opt_kwargs=dict(ok))
    mfu = tps * fpt / bench._chip_peak(jax.devices()[0])
    return tps, round(mfu, 4), roofline


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", required=True,
                    choices=["full", "noln", "nogelu"])
    ap.add_argument("--no-lint", action="store_true",
                    help="skip the tpu_lint preflight gate")
    args = ap.parse_args()
    from paddle_tpu.analysis.preflight import preflight

    preflight("train_profile", no_lint=args.no_lint)
    t0 = time.time()
    tps, mfu, roofline = run(args.mode)
    # roofline: XLA cost-model MFU/bandwidth for the compiled step
    # (see paddle_tpu/profiler/roofline.py) next to the analytic mfu
    print(json.dumps({"mode": args.mode, "tokens_per_sec": round(tps, 1),
                      "mfu": mfu, "roofline": roofline,
                      "wall": round(time.time() - t0, 1)}))


if __name__ == "__main__":
    main()
